"""Continuous-batching generation engine over the sharded decode stack.

The Orca (OSDI '22) scheduling idea on this framework's mesh: a fixed
bank of decode slots runs one compiled single-token step per tick, and
requests are inserted into / evicted from slots BETWEEN ticks — a
finishing sequence hands its slot and pages to the next queued request
at the next step boundary instead of holding the batch hostage until
the longest member drains.  Admission is a free-page watermark: a
request enters only when its slot's data-parallel group can cover the
request's WHOLE page footprint (prompt + budgeted new tokens), so a
running sequence can never hit page exhaustion mid-stream.

Everything compiled is shape-stable by construction — the decode step
always sees all ``n_slots`` slots (idle ones masked by ``seq_len == 0``
and sentinel page ids), prompts pad to power-of-two length buckets — so
steady-state serving triggers ZERO recompiles after warmup, asserted
through the :class:`~tpuscratch.serve.decode.CompileCounter` hooks.
Scheduling itself is host-side Python between compiled steps, the same
layering as the reference's rank-0 driver loops.

``GenerateReport`` mirrors ``models/trainer.TrainReport``; prefill and
decode are bracketed by ``runtime.profiling.Timeline`` spans, pulled
into the report as aggregate seconds.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from tpuscratch.ft.chaos import bind_sink
from tpuscratch.models.transformer import TransformerConfig, init_params
from tpuscratch.obs.metrics import CompileCounter, MetricsRegistry
from tpuscratch.obs.sink import NullSink
from tpuscratch.obs.trace import FlightRecorder, emit_phase_totals
from tpuscratch.runtime.profiling import Timeline
from tpuscratch.serve.decode import (
    build_context_prefill,
    build_decode_step,
    build_prefill,
    build_verify_step,
    check_serve_mesh,
    propose_draft,
)
from tpuscratch.serve.kvcache import (
    CacheGeometry,
    PageAllocator,
    PrefixCache,
    init_kv_cache,
)
from tpuscratch.serve.sampling import (
    accept_speculative,
    request_key,
    request_keys,
    sample_batch,
)

#: ServeConfig.kv_dtype spellings -> cache buffer dtype (the fp32 /
#: int8 / fp8-e4m3 ladder; both quantized rungs carry scale planes)
_KV_DTYPES = {
    "float32": jnp.float32,
    "int8": jnp.int8,
    "fp8": jnp.float8_e4m3fn,
}

#: ServeConfig.fused_attention spellings -> the ops.attention ``fused``
#: argument ("auto" follows the backend policy: fused Pallas sweep on a
#: real TPU, dense XLA oracle elsewhere)
_FUSED_MODES = {"auto": None, "on": True, "off": False}


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (the model itself comes from ``TransformerConfig``)."""

    n_slots: int = 8          # fixed decode-batch width (all dp groups)
    n_pages: int = 64         # KV pages PER dp group
    page_size: int = 8        # tokens per page
    max_seq: int = 64         # per-request prompt + generated cap
    vocab: int = 32           # token-id space (tied embed/unembed)
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0            # 0 = full distribution
    seed: int = 0             # sampling + embedding seed
    # extra prefill attempts per request before QUARANTINE.  0 (default)
    # keeps the legacy contract: a failed admission requeues the request
    # and re-raises to the caller.  > 0: failed admissions are retried
    # in-engine (transient faults complete) and a request that exhausts
    # the budget is quarantined — reported, never requeued — so one
    # poison request cannot livelock the engine.
    retry_budget: int = 0
    # cache-byte lever: "float32" (exact), "int8", or "fp8" (e4m3) —
    # the quantized rungs store pages at one byte per element with
    # per-page per-head scales, ~4x fewer cache bytes per token (the
    # decode gather's roofline); fp8 is the accuracy-per-byte rung
    # (floating grid, outlier-robust) at the same bytes as int8.  See
    # serve/kvcache.py for the ladder table.
    kv_dtype: str = "float32"
    # decode-sweep kernel: "auto" (fused Pallas paged-attention kernel
    # on a real TPU, dense XLA oracle elsewhere), "on" (force fused —
    # interpret-mode Pallas off-TPU, the equivalence-test path), "off"
    # (force the dense oracle).  Applies to decode, speculative verify,
    # and chunked context prefill — the three paths share one kernel
    # family (ops.attention.paged_attention).
    fused_attention: str = "auto"
    # HBM-sweep-amortization lever: draft tokens scored per verify sweep
    # (0 = speculation off).  > 0 replaces the one-token decode program
    # with ONE (spec_k + 1)-token verify program; accepted prefixes emit
    # up to spec_k + 1 tokens per cache sweep, and the acceptance rule
    # preserves the sampling distribution exactly (bit-identical output
    # under greedy; serve/sampling.accept_speculative)
    spec_k: int = 0
    # suffix length for the self-drafting prompt-lookup match
    spec_ngram: int = 2
    # cross-request KV prefix sharing (off by default): admissions whose
    # prompts share a full-page-aligned prefix with LIVE cached pages
    # attach to them (allocator refcount +1) instead of re-prefilling —
    # only the unshared tail runs through the context-prefill program,
    # so prefill FLOPs and freshly-written KV bytes drop with the share
    # ratio; copy-on-write protects shared pages from in-place writes
    prefix_share: bool = False
    # chunked prefill (0 = off): prompts advance at most N tokens per
    # engine tick through the context-prefill program instead of paying
    # their whole length inside one tick — one long admission stops
    # blocking every resident decode stream (bounds per-token p99)
    chunk_prefill: int = 0

    @property
    def max_pages(self) -> int:
        """Page-table width: the per-request page footprint ceiling."""
        return -(-self.max_seq // self.page_size)


@dataclasses.dataclass(frozen=True)
class Request:
    rid: int                  # unique per engine (keys the PRNG stream)
    prompt: tuple[int, ...]   # token ids
    max_new: int              # generation budget (>= 1)


@dataclasses.dataclass(frozen=True)
class GenerateReport:
    """What a drain produced — the serving twin of ``TrainReport``.

    Speculative accounting reconciles by construction:
    ``tokens_generated == prefills + slot_steps + accepted`` — every
    emitted token is a prefill token, a verify sweep's base token (one
    per active slot per tick, speculation on or off), or an accepted
    draft token (ex24 asserts this identity on a live run)."""

    completed: int
    tokens_generated: int
    decode_steps: int
    prefills: int
    decode_compiles: int
    prefill_compiles: int
    prefill_s: float
    decode_s: float
    outputs: tuple[tuple[int, tuple[int, ...]], ...]  # (rid, tokens) by rid
    quarantined: tuple[int, ...] = ()  # rids dropped THIS drain (budget spent)
    slot_steps: int = 0   # active-slot decode/verify invocations
    drafted: int = 0      # speculative draft tokens scored
    accepted: int = 0     # draft tokens accepted into outputs
    # prefix-sharing accounting (the static half of the sharing claim):
    # every prompt token is either COMPUTED through a prefill program
    # (prefill_tokens) or SERVED from a shared page (shared_tokens), so
    # prefill_tokens + shared_tokens == sum of admitted prompt lengths
    # and both legs drop deterministically with the share ratio
    prefill_tokens: int = 0
    shared_tokens: int = 0
    cow_pages: int = 0          # copy-on-write page copies this drain
    fresh_kv_bytes: float = 0.0  # K/V bytes freshly written this drain

    @property
    def accept_len_mean(self) -> Optional[float]:
        """Mean accepted draft length per verify sweep (None: no sweeps)."""
        if self.slot_steps == 0:
            return None
        return self.accepted / self.slot_steps

    @property
    def shared_frac(self) -> float:
        """Fraction of admitted prompt tokens served from shared pages."""
        total = self.prefill_tokens + self.shared_tokens
        return self.shared_tokens / total if total else 0.0


@dataclasses.dataclass
class _Slot:
    rid: int
    prompt: tuple[int, ...]   # kept for deterministic replay on recovery
    pages: list[int]          # LOCAL page ids in this slot's group
    n_cached: int             # tokens whose K/V are in the cache
    max_new: int
    last_token: int
    generated: list[int]
    # prompt tokens NOT yet prefilled (context-prefill admissions only):
    # a slot with pending tokens is PREFILLING — it advances one chunk
    # per tick and joins the decode bank when the tail drains
    pending: tuple[int, ...] = ()


#: profiling spans kept on the engine's Timeline — a recent window, not
#: engine-lifetime history (a continuously-serving engine would otherwise
#: grow one Span per tick without bound)
_MAX_SPANS = 1024


def init_embed(seed: int, vocab: int, d_model: int) -> jax.Array:
    """Tied token embedding / unembedding table (V, d)."""
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.standard_normal((vocab, d_model)).astype(np.float32)
        / np.sqrt(d_model)
    )


def _bucket(n: int) -> int:
    """Prompt shape bucket: next power of two, floor 8 — bounds prefill
    compiles at log2(max_seq) programs."""
    b = 8
    while b < n:
        b *= 2
    return b


class ServeEngine:
    """Sharded continuous-batching engine.  ``submit`` queues requests,
    ``step`` runs one admission + decode tick, ``run`` drains.

    Slot ``s`` belongs to dp group ``s // (n_slots / dp_size)`` — the
    contiguous chunk P(dp) sharding hands that group — and its pages come
    from that group's own :class:`PageAllocator` (ids are group-local,
    matching the dp-sharded pages axis of the cache).

    ``sink`` (an ``obs.sink.Sink``; default the no-op ``NullSink``)
    receives one ``serve/tick`` event per tick plus a ``serve/report`` +
    metrics snapshot per drain; ``self.metrics`` is the live
    ``obs.MetricsRegistry`` regardless of sink.  ``recorder`` (an
    ``obs.trace.FlightRecorder``; a fresh bounded one when absent — the
    flight recorder is always on) collects the prefill/decode spans via
    the engine's Timeline for Chrome-trace export; per-phase totals are
    emitted as cumulative ``trace/phase`` events at each drain."""

    def __init__(self, mesh: Mesh, cfg: TransformerConfig, scfg: ServeConfig,
                 params: Optional[dict] = None,
                 embed: Optional[jax.Array] = None,
                 dp: str = "dp", sp: str = "sp",
                 sink=None, chaos=None, recorder=None):
        check_serve_mesh(mesh, cfg, dp, sp)
        self._dp_size = mesh.shape[dp]
        if scfg.n_slots % self._dp_size:
            raise ValueError(
                f"n_slots {scfg.n_slots} not divisible by dp size "
                f"{self._dp_size}"
            )
        if scfg.max_seq > scfg.n_pages * scfg.page_size:
            raise ValueError(
                f"max_seq {scfg.max_seq} exceeds one group's pool "
                f"({scfg.n_pages} pages x {scfg.page_size})"
            )
        if scfg.kv_dtype not in _KV_DTYPES:
            raise ValueError(
                f"kv_dtype {scfg.kv_dtype!r} not in {sorted(_KV_DTYPES)}"
            )
        if scfg.fused_attention not in _FUSED_MODES:
            raise ValueError(
                f"fused_attention {scfg.fused_attention!r} not in "
                f"{sorted(_FUSED_MODES)}"
            )
        if scfg.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {scfg.spec_k}")
        if scfg.spec_ngram < 1:
            raise ValueError(
                f"spec_ngram must be >= 1, got {scfg.spec_ngram}"
            )
        if scfg.chunk_prefill < 0:
            raise ValueError(
                f"chunk_prefill must be >= 0, got {scfg.chunk_prefill}"
            )
        if (scfg.prefix_share or scfg.chunk_prefill) and scfg.retry_budget:
            raise ValueError(
                "retry_budget composes with the monolithic admission "
                "path only; context-prefill admissions (prefix_share / "
                "chunk_prefill) keep the legacy raise-through contract"
            )
        self.mesh, self.cfg, self.scfg = mesh, cfg, scfg
        self._kv_jnp_dtype = _KV_DTYPES[scfg.kv_dtype]
        self._quantized = scfg.kv_dtype != "float32"
        self._fused = _FUSED_MODES[scfg.fused_attention]
        self.geom = CacheGeometry(
            cfg.n_layers, scfg.n_pages, scfg.page_size, cfg.n_heads,
            cfg.d_head,
        )
        self.params = (
            params if params is not None else init_params(scfg.seed, cfg)
        )
        self.embed = (
            embed if embed is not None
            else init_embed(scfg.seed, scfg.vocab, cfg.d_model)
        )
        if self.embed.shape != (scfg.vocab, cfg.d_model):
            raise ValueError(
                f"embed {self.embed.shape} != ({scfg.vocab}, {cfg.d_model})"
            )
        self._embed_np = np.asarray(self.embed)
        # the fresh pool COMMITS to its canonical sharding up front:
        # an uncommitted zeros pytree carries SingleDeviceSharding, so
        # the first admission would compile each prefill program against
        # THAT and the second against the program-output NamedSharding —
        # a hidden per-bucket XLA recompile (~100s of ms) on the second
        # admission that CompileCounter cannot see (the jaxpr is cached;
        # only the sharding key changed).  Committing makes every
        # invocation see one sharding, so each program compiles once.
        from tpuscratch.serve.kvcache import kv_cache_spec

        self._kv_sharding = {
            name: NamedSharding(mesh, spec)
            for name, spec in kv_cache_spec(dp, sp, self._quantized).items()
        }
        self._kv = self._fresh_kv()
        self._allocators = [
            PageAllocator(scfg.n_pages) for _ in range(self._dp_size)
        ]
        self._slots: list[Optional[_Slot]] = [None] * scfg.n_slots
        self._slots_per_group = scfg.n_slots // self._dp_size
        self._queue: collections.deque[Request] = collections.deque()
        self._seen_rids: set[int] = set()
        self._chaos = chaos  # ft.ChaosPlan or None: "serve/prefill" site
        self._quarantined: dict[int, str] = {}  # rid -> last error
        self._seed_key = jax.random.key(scfg.seed)
        self.recorder = (
            recorder if recorder is not None else FlightRecorder()
        )
        self.timeline = Timeline(self.recorder)
        # observability: every tick updates the registry (host-side
        # attribute writes, < 2% of a compiled step) and, when a sink is
        # attached, emits one JSONL event — queue depth, free-page
        # watermark, tick latency, insert/evict counts, compile counts
        self.metrics = MetricsRegistry()
        self.sink = sink if sink is not None else NullSink()
        bind_sink(chaos, self.sink)  # injected ft/fault events join the stream
        self._tick = 0
        self.sink.emit(
            "serve/engine",
            n_slots=scfg.n_slots, n_pages=scfg.n_pages,
            page_size=scfg.page_size, max_seq=scfg.max_seq,
            dp_size=self._dp_size, n_layers=cfg.n_layers,
            n_heads=cfg.n_heads, d_model=cfg.d_model,
            kv_dtype=scfg.kv_dtype, spec_k=scfg.spec_k,
        )
        self.decode_counter = CompileCounter()
        self.prefill_counter = CompileCounter()
        # speculation swaps the one-token decode program for ONE fixed
        # (spec_k + 1)-token verify program — still a single compile,
        # still counted by decode_counter
        if scfg.spec_k > 0:
            self._decode = build_verify_step(
                mesh, cfg, self.geom, scfg.spec_k, dp=dp, sp=sp,
                counter=self.decode_counter, quantized=self._quantized,
                fused=self._fused,
            )
        else:
            self._decode = build_decode_step(
                mesh, cfg, self.geom, dp=dp, sp=sp,
                counter=self.decode_counter, quantized=self._quantized,
                fused=self._fused,
            )
        self._prefills: dict[int, object] = {}  # bucket len -> program
        self._dp, self._sp = dp, sp
        # context-prefill layers (both OFF by default: self._ctx stays
        # None and the admission path is byte-for-byte the legacy one)
        self._ctx_mode = scfg.prefix_share or scfg.chunk_prefill > 0
        self._chunk = (
            scfg.chunk_prefill if scfg.chunk_prefill > 0 else scfg.page_size
        )
        self._ctx = (
            build_context_prefill(
                mesh, cfg, self.geom, self._chunk, dp=dp, sp=sp,
                counter=self.prefill_counter, quantized=self._quantized,
                fused=self._fused,
            )
            if self._ctx_mode else None
        )
        self._tries: Optional[list[PrefixCache]] = (
            [PrefixCache(scfg.page_size) for _ in range(self._dp_size)]
            if scfg.prefix_share else None
        )
        self._unembed = jax.jit(lambda o, e: o @ e.T)
        self._decode_steps = 0
        self._prefill_count = 0
        self._tokens_generated = 0
        self._slot_steps = 0
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._prefill_s = 0.0
        self._decode_s = 0.0
        self._prefill_tokens = 0
        self._shared_tokens = 0
        self._fresh_tokens = 0   # tokens whose K/V this engine wrote
        self._cow_pages = 0

    # ---- introspection (tests + report) --------------------------------

    @property
    def decode_compiles(self) -> int:
        return self.decode_counter.count

    @property
    def prefill_compiles(self) -> int:
        return self.prefill_counter.count

    def free_pages(self) -> list[int]:
        """Per-group free-page counts (the leak check reads this)."""
        return [a.n_free for a in self._allocators]

    @property
    def kv_cache_bytes(self) -> int:
        """Total cache-pool bytes (pages + quantization scales) — the
        static quantity the int8 lever shrinks; ``obs.ledger`` does the
        accounting so bench rows and regression tests share it."""
        from tpuscratch.obs.ledger import kv_cache_bytes

        return kv_cache_bytes(self._kv)

    @property
    def kv_bytes_per_token(self) -> float:
        """Cache bytes per token of pool capacity (pages + scales over
        ``dp_size * n_pages * page_size`` token slots)."""
        return self.kv_cache_bytes / (self._dp_size * self.geom.max_tokens)

    @property
    def cached_pages(self) -> int:
        """Pages the NEXT decode sweep will gather: sum over live slots
        of ceil(cached length / page_size).  The bench's roofline
        accounting multiplies this by the pool's exact per-token bytes
        (``kv_bytes_per_token`` — payload + amortized scale planes) to
        get the HBM bytes one tick's sweep moves, the denominator-free
        half of the achieved-fraction-of-peak measurement
        (``bench.decode_bench``)."""
        page = self.scfg.page_size
        return sum(
            -(-s.n_cached // page) for s in self._slots if s is not None
        )

    @property
    def tokens_generated(self) -> int:
        """Engine-lifetime emitted tokens (benches read deltas)."""
        return self._tokens_generated

    @property
    def slot_steps(self) -> int:
        """Engine-lifetime active-slot decode/verify invocations."""
        return self._slot_steps

    @property
    def spec_drafted(self) -> int:
        return self._spec_drafted

    @property
    def spec_accepted(self) -> int:
        return self._spec_accepted

    @property
    def prefill_tokens(self) -> int:
        """Engine-lifetime prompt tokens COMPUTED through a prefill
        program (monolithic or context-chunk) — the prefill-FLOP leg
        prefix sharing shrinks."""
        return self._prefill_tokens

    @property
    def shared_tokens(self) -> int:
        """Engine-lifetime prompt tokens served from shared pages."""
        return self._shared_tokens

    @property
    def cow_pages(self) -> int:
        """Engine-lifetime copy-on-write page copies."""
        return self._cow_pages

    @property
    def fresh_kv_bytes(self) -> float:
        """Engine-lifetime K/V bytes freshly written into the pool
        (prefilled prompt tokens + generated tokens, at this pool's
        exact per-token byte cost incl. quantization scales) — shared
        admissions write none for their shared prefix, so this drops
        with the share ratio.  Static accounting, not sampled: token
        counts are exact and the per-token bytes come from the pool
        geometry (``obs.ledger.kv_cache_bytes`` over capacity)."""
        return self._fresh_tokens * self.kv_bytes_per_token

    @property
    def n_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def n_queued(self) -> int:
        return len(self._queue)

    @property
    def quarantined(self) -> dict[int, str]:
        """{rid: last error} of requests dropped after the retry budget."""
        return dict(self._quarantined)

    def _group_of(self, slot: int) -> int:
        return slot // self._slots_per_group

    def _last_span_s(self) -> float:
        """Seconds of the span just recorded; trims the Timeline to a
        recent window so a long-lived engine's span list stays bounded."""
        s = self.timeline.spans[-1].seconds
        if len(self.timeline.spans) > _MAX_SPANS:
            del self.timeline.spans[: -_MAX_SPANS]
        return s

    def _fresh_kv(self) -> dict:
        """A zeroed pool committed to the canonical cache sharding."""
        return {
            name: jax.device_put(leaf, self._kv_sharding[name])
            for name, leaf in init_kv_cache(
                self.geom, self._dp_size, self._kv_jnp_dtype
            ).items()
        }

    def _free_slot_pages(self, slot: int, st: _Slot) -> None:
        """Drop this slot's holds; pages whose LAST holder left leave
        the prefix trie too (a dead page must never be matched)."""
        group = self._group_of(slot)
        released = self._allocators[group].free(st.pages)
        if self._tries is not None and released:
            self._tries[group].drop(released)

    def _recover_cache(self) -> None:
        """A compiled call raised mid-flight: its DONATED cache buffers
        may already be consumed, so serving cannot continue on the old
        pool.  Reset it and requeue every in-flight request from its
        original prompt — rids key the PRNG streams, so the replay
        regenerates the SAME tokens and a caller that catches the error
        and drains again loses nothing.  The prefix trie clears with the
        pool: a zeroed page holds no one's prefix."""
        for s, st in enumerate(self._slots):
            if st is None:
                continue
            self._free_slot_pages(s, st)
            self._slots[s] = None
            self._queue.appendleft(
                Request(rid=st.rid, prompt=st.prompt, max_new=st.max_new)
            )
        if self._tries is not None:
            for trie in self._tries:
                trie.clear()
        self._kv = self._fresh_kv()

    # ---- request lifecycle ---------------------------------------------

    def submit(self, req: Request) -> None:
        if req.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {req.max_new}")
        if req.rid < 0:
            raise ValueError(f"rid must be >= 0, got {req.rid}")
        if not req.prompt:
            raise ValueError("empty prompt")
        if len(req.prompt) + req.max_new > self.scfg.max_seq:
            raise ValueError(
                f"request {req.rid}: prompt {len(req.prompt)} + max_new "
                f"{req.max_new} exceeds max_seq {self.scfg.max_seq}"
            )
        if any(t < 0 or t >= self.scfg.vocab for t in req.prompt):
            raise ValueError(f"request {req.rid}: token id out of vocab")
        if req.rid in self._seen_rids:
            # rids key the PRNG streams AND the report's outputs map — a
            # reuse would silently drop one output and sample identical
            # token streams for both
            raise ValueError(f"request id {req.rid} already used")
        self._seen_rids.add(req.rid)
        self._queue.append(req)

    def admit_prefilled(self, req: Request, slot: int, pages: list[int],
                        first_token: int) -> None:
        """Install an EXTERNALLY-prefilled request directly into
        ``slot`` — the disaggregated handoff path (serve/disagg.py):
        the request's whole prompt K/V already sits in THIS engine's
        cache pool under ``pages`` (migrated in from the prefill
        slice), and ``first_token`` is the token its prefill sampled
        (stream position 0), so decode continues exactly where the
        monolithic admission would.  ``pages`` must have been allocated
        from the slot's group allocator by the caller and must cover
        the request's full footprint (prompt + budget); the slot must
        be free.  Counted as an emitted token but NOT as an engine
        prefill — this engine ran no prefill program for it."""
        if self._slots[slot] is not None:
            raise ValueError(f"slot {slot} is busy")
        if req.rid in self._seen_rids:
            raise ValueError(f"request id {req.rid} already used")
        need = self.geom.pages_for(len(req.prompt) + req.max_new)
        if len(pages) < need:
            raise ValueError(
                f"request {req.rid} needs {need} pages, got {len(pages)}"
            )
        self._seen_rids.add(req.rid)
        self._tokens_generated += 1
        self._slots[slot] = _Slot(
            rid=req.rid, prompt=req.prompt, pages=list(pages),
            n_cached=len(req.prompt), max_new=req.max_new,
            last_token=first_token, generated=[first_token],
        )

    def _share_plan(self, req: Request,
                    group: int) -> tuple[list[int], bool, int]:
        """(shared pages, full_aligned, pages to NEWLY allocate) for
        admitting ``req`` into ``group`` — the refcount-aware admission
        arithmetic the watermark gate and ``_admit_ctx`` share, so the
        gate can never promise pages the admission then over-draws.

        ``full_aligned`` marks the whole-prompt page-aligned match: the
        admission must RE-SCORE the last prompt position for its
        logits, and that write needs a private copy of the last shared
        page — so one page of the allocation is the copy-on-write
        budget (the shared page itself stays untouched for its other
        holders)."""
        shared = (
            self._tries[group].match(req.prompt)
            if self._tries is not None else []
        )
        m = len(shared)
        n_tok = len(req.prompt)
        full_aligned = m > 0 and m * self.geom.page_size == n_tok
        total = self.geom.pages_for(n_tok + req.max_new)
        need = total - m + (1 if full_aligned else 0)
        return shared, full_aligned, need

    def _find_slot(self, req: Request) -> Optional[int]:
        needs: dict[int, int] = {}  # the plan depends only on the group
        for s, slot in enumerate(self._slots):
            if slot is None:
                group = self._group_of(s)
                # refcount-aware watermark: a shared-prefix admission
                # allocates only its UNSHARED pages, so the gate counts
                # those — not the request's whole footprint (shared
                # pages are already live and consume no free capacity)
                if group not in needs:
                    needs[group] = self._share_plan(req, group)[2]
                if self._allocators[group].n_free >= needs[group]:
                    return s
        return None

    def _sample(self, keys, logits):
        return sample_batch(
            keys, logits, self.scfg.temperature, self.scfg.top_k
        )

    def _admit(self, req: Request, slot: int,
               finished: Optional[list] = None) -> bool:
        """Prefill ``req`` into ``slot``; True when the slot was taken.

        With ``prefix_share`` or ``chunk_prefill`` set the admission
        routes through :meth:`_admit_ctx` (context-prefill path);
        otherwise this is the legacy monolithic program, byte-for-byte.

        With ``scfg.retry_budget == 0`` (default) a prefill failure keeps
        the legacy contract: grant returned, request requeued at the
        head, cache recovered, exception re-raised.  With a budget,
        failed attempts are retried in-engine (the cache reset + replay
        between attempts, so transient faults complete with outputs
        byte-identical to a fault-free run) and a request that exhausts
        ``1 + retry_budget`` attempts is QUARANTINED: its grant is
        returned, it never requeues, and the engine moves on — the
        deterministic-poison livelock the unconditional requeue had."""
        if self._ctx_mode:
            return self._admit_ctx(req, slot, finished)
        geom, scfg = self.geom, self.scfg
        group = self._group_of(slot)
        pages = self._allocators[group].alloc(
            geom.pages_for(len(req.prompt) + req.max_new)
        )
        assert pages is not None  # _find_slot checked the watermark
        n_tok = len(req.prompt)
        bucket = _bucket(n_tok)
        if bucket not in self._prefills:
            self._prefills[bucket] = build_prefill(
                self.mesh, self.cfg, geom, dp=self._dp, sp=self._sp,
                counter=self.prefill_counter, quantized=self._quantized,
            )
        x = np.zeros((bucket, self.cfg.d_model), np.float32)
        x[:n_tok] = self._embed_np[list(req.prompt)]
        page_rows = np.full(
            (self._dp_size, scfg.max_pages), geom.n_pages, np.int32
        )
        page_rows[group, : len(pages)] = pages

        def attempt() -> int:
            if self._chaos is not None:
                self._chaos.maybe_fail("serve/prefill", key=req.rid,
                                       op="serve/prefill")
            with self.timeline.span("serve/prefill"):
                out, self._kv = self._prefills[bucket](
                    self.params, self._kv, jnp.asarray(x),
                    jnp.asarray(page_rows), jnp.int32(n_tok),
                )
                logits = self._unembed(out[n_tok - 1][None], self.embed)
                return int(
                    self._sample(
                        request_key(scfg.seed, req.rid, 0)[None], logits
                    )[0]
                )

        if scfg.retry_budget == 0:
            try:
                tok = attempt()
            except Exception:
                # a failing prefill (transient device error, first-bucket
                # compile OOM) must not bleed the pool dry across retries:
                # return the grant, put the request back at the head, and
                # reset the (possibly donated-and-consumed) cache — every
                # in-flight request requeues for deterministic replay
                self._allocators[group].free(pages)
                self._queue.appendleft(req)
                self._recover_cache()
                raise
        else:
            tok = None
            attempts = 1 + scfg.retry_budget
            for a in range(attempts):
                try:
                    tok = attempt()
                    break
                except Exception as exc:
                    self.metrics.counter("serve/prefill_failures").inc()
                    # the donated cache may be consumed: reset it and
                    # requeue every IN-FLIGHT request (rids key the PRNG
                    # streams, so their replay is byte-identical); THIS
                    # request keeps its grant for the next attempt
                    self._recover_cache()
                    if a + 1 >= attempts:
                        self._allocators[group].free(pages)
                        reason = f"{type(exc).__name__}: {exc}"
                        self._quarantined[req.rid] = reason
                        self.metrics.counter("serve/quarantined").inc()
                        self.sink.emit("ft/quarantine", rid=req.rid,
                                       attempts=attempts, error=reason)
                        return False
                    if self.sink.enabled:
                        self.sink.emit("ft/prefill_retry", rid=req.rid,
                                       attempt=a + 1,
                                       error=f"{type(exc).__name__}: {exc}")
        self._prefill_s += self._last_span_s()
        self._prefill_count += 1
        self._tokens_generated += 1
        self._prefill_tokens += n_tok
        self._fresh_tokens += n_tok
        self._slots[slot] = _Slot(
            rid=req.rid, prompt=req.prompt, pages=pages, n_cached=n_tok,
            max_new=req.max_new, last_token=tok, generated=[tok],
        )
        return True

    def _admit_ctx(self, req: Request, slot: int,
                   finished: Optional[list] = None) -> bool:
        """Context-prefill admission: attach to shared prefix pages (if
        ``prefix_share`` matched any), allocate only the unshared
        footprint, and queue the unshared prompt tail as the slot's
        ``pending`` chunk stream.

        - tail path: the tail (>= 1 token) prefills through the
          context program, attending the shared pages it skipped;
        - full-aligned path: EVERY prompt page was matched, so the only
          compute left is re-scoring the last prompt position for its
          logits — and since that write lands in the last shared page,
          the page is copy-on-written into this admission's reserved
          budget first (the other holders' view is untouched).

        With ``chunk_prefill == 0`` (prefix sharing alone) the whole
        tail drains inside this call — monolithic admission latency
        semantics, chunked numerics; with a chunk budget the tail
        advances one chunk per engine tick instead (``_ctx_step``).

        Failures keep the legacy contract: the compiled-call exception
        path resets the donated pool and requeues every in-flight
        request (this one included) for deterministic replay."""
        geom, scfg = self.geom, self.scfg
        group = self._group_of(slot)
        alloc = self._allocators[group]
        if self._chaos is not None:
            try:
                self._chaos.maybe_fail("serve/prefill", key=req.rid,
                                       op="serve/prefill")
            except Exception:
                self._queue.appendleft(req)
                raise
        n_tok = len(req.prompt)
        shared, full_aligned, need = self._share_plan(req, group)
        priv = alloc.alloc(need)
        assert priv is not None  # _find_slot ran the same arithmetic
        if shared:
            alloc.share(shared)
        if full_aligned:
            # copy-on-write: the re-score must write position
            # n_tok - 1, which lives in the last shared page
            self._copy_page(group, shared[-1], priv[0])
            if self._tries is not None:
                self._tries[group].drop(alloc.free([shared[-1]]))
            pages = shared[:-1] + priv
            n_cached = n_tok - 1
            self._cow_pages += 1
        else:
            pages = shared + priv
            n_cached = len(shared) * geom.page_size
        self._shared_tokens += n_cached
        self._slots[slot] = _Slot(
            rid=req.rid, prompt=req.prompt, pages=pages, n_cached=n_cached,
            max_new=req.max_new, last_token=0, generated=[],
            pending=req.prompt[n_cached:],
        )
        self._prefill_count += 1
        if scfg.chunk_prefill == 0:
            # share-only mode: the tail drains inside the admission
            while (self._slots[slot] is not None
                   and self._slots[slot].pending):
                self._ctx_step([slot], finished)
        return True

    def _ensure_private(self, slot: int, page_index: int) -> None:
        """Copy-on-write guard on the write paths: a slot about to
        write into table entry ``page_index`` must hold that page
        EXCLUSIVELY — if other requests share it, the payload is copied
        into a fresh page, the table entry swapped, and this slot's
        hold on the shared page dropped.  Unreachable in the supported
        admission flows (writes always land past the shared prefix;
        the full-aligned re-score pre-copies at admission), so a grant
        failure here is a logic error, not back-pressure."""
        st = self._slots[slot]
        group = self._group_of(slot)
        alloc = self._allocators[group]
        page = st.pages[page_index]
        if alloc.refcount(page) <= 1:
            return
        fresh = alloc.alloc(1)
        if fresh is None:
            raise RuntimeError(
                f"copy-on-write of shared page {page} (slot {slot}) "
                "found an empty pool — admission reserved too little"
            )
        self._copy_page(group, page, fresh[0])
        st.pages[page_index] = fresh[0]
        if self._tries is not None:
            self._tries[group].drop(alloc.free([page]))
        else:
            alloc.free([page])
        self._cow_pages += 1

    def _copy_page(self, group: int, src: int, dst: int) -> None:
        """Copy one page's payload (and, for int8 pools, its scale
        rows) between group-local ids — the copy-on-write data move.
        Host-level functional update between compiled steps; rare by
        construction (once per fully-shared aligned admission)."""
        off = group * self.geom.n_pages
        for name, buf in self._kv.items():
            self._kv[name] = buf.at[:, off + dst].set(buf[:, off + src])

    def _ctx_step(self, slots: list[int], finished: Optional[list]) -> None:
        """One context-prefill chunk for every PREFILLING slot: each
        advances up to ``self._chunk`` pending prompt tokens through
        the ONE compiled context program (K/V written to its pages,
        ragged-causal attention over its cached prefix).  A slot whose
        pending tail drains samples its first token (the same
        ``request_key(seed, rid, 0)`` draw the monolithic prefill
        makes), registers its full prompt pages in the prefix trie, and
        joins the decode bank — or is evicted right here when its
        budget was one token."""
        scfg, geom = self.scfg, self.geom
        n, C = scfg.n_slots, self._chunk
        x = np.zeros((n, C, self.cfg.d_model), np.float32)
        tables = np.full((n, scfg.max_pages), geom.n_pages, np.int32)
        write_pages = np.full((n, C), geom.n_pages, np.int32)
        write_offs = np.zeros((n, C), np.int32)
        seq_lens = np.zeros((n,), np.int32)
        takes: dict[int, int] = {}
        for s in slots:
            st = self._slots[s]
            take = min(C, len(st.pending))
            takes[s] = take
            # CoW guard BEFORE the tables snapshot: a swapped page must
            # be what the program gathers
            for pi in range(st.n_cached // geom.page_size,
                            (st.n_cached + take - 1) // geom.page_size + 1):
                self._ensure_private(s, pi)
            x[s, :take] = self._embed_np[list(st.pending[:take])]
            tables[s, : len(st.pages)] = st.pages
            for j in range(take):
                pos = st.n_cached + j
                write_pages[s, j] = st.pages[pos // geom.page_size]
                write_offs[s, j] = pos % geom.page_size
            seq_lens[s] = st.n_cached + 1
        done = [s for s in slots
                if takes[s] == len(self._slots[s].pending)]
        try:
            with self.timeline.span("serve/prefill"):
                out, self._kv = self._ctx(
                    self.params, self._kv, jnp.asarray(x),
                    jnp.asarray(tables), jnp.asarray(write_pages),
                    jnp.asarray(write_offs), jnp.asarray(seq_lens),
                )
                if done:
                    # STATIC shapes over the whole slot bank (the
                    # decode tick's rule): a variable done-set length
                    # would key fresh unembed/key/sample compiles mid-
                    # stream; idle rows sample with dummy keys, results
                    # discarded
                    last = np.zeros((n,), np.int64)
                    rids = np.zeros((n,), np.int32)
                    for s in done:
                        last[s] = takes[s] - 1
                        rids[s] = self._slots[s].rid
                    logits = self._unembed(
                        out[jnp.arange(n), jnp.asarray(last)], self.embed
                    )
                    keys = request_keys(
                        self._seed_key, jnp.asarray(rids),
                        jnp.zeros((n,), jnp.int32),
                    )
                    first = np.asarray(self._sample(keys, logits))
        except Exception:
            self._recover_cache()  # donated kv may be consumed; replay
            raise
        self._prefill_s += self._last_span_s()
        for s in slots:
            st = self._slots[s]
            take = takes[s]
            st.n_cached += take
            st.pending = st.pending[take:]
            self._prefill_tokens += take
            self._fresh_tokens += take
        for s in done:
            st = self._slots[s]
            tok = int(first[s])
            st.last_token = tok
            st.generated = [tok]
            self._tokens_generated += 1
            if self._tries is not None:
                self._tries[self._group_of(s)].insert(st.prompt, st.pages)
            if len(st.generated) >= st.max_new:
                out_pair = self._evict(s)
                if finished is not None:
                    finished.append(out_pair)

    def _evict(self, slot: int) -> tuple[int, tuple[int, ...]]:
        st = self._slots[slot]
        assert st is not None
        self._free_slot_pages(slot, st)
        self._slots[slot] = None
        return st.rid, tuple(st.generated)

    # ---- the tick ------------------------------------------------------

    def step(self) -> list[tuple[int, tuple[int, ...]]]:
        """One engine tick: admit what fits, decode one token for every
        active slot, evict what finished.  Returns the finished
        ``(rid, tokens)`` pairs.  Each tick updates ``self.metrics``
        (tick latency, queue depth, free-page watermark, insert/evict
        counts, compile counts) and emits one sink event."""
        t0 = time.perf_counter()
        prefills0 = self._prefill_count
        tokens0 = self._tokens_generated
        accepted0 = self._spec_accepted
        ptok0 = self._prefill_tokens
        finished = self._tick_inner()
        self._observe_tick(
            time.perf_counter() - t0,
            inserted=self._prefill_count - prefills0,
            evicted=len(finished),
            tokens=self._tokens_generated - tokens0,
            accepted=self._spec_accepted - accepted0,
            prefill_tokens=self._prefill_tokens - ptok0,
        )
        return finished

    def _observe_tick(self, tick_s: float, inserted: int, evicted: int,
                      tokens: int, accepted: int = 0,
                      prefill_tokens: int = 0) -> None:
        m = self.metrics
        self._tick += 1
        free_min = min(a.n_free for a in self._allocators)
        m.histogram("serve/tick_s").observe(tick_s)
        m.gauge("serve/queue_depth").set(self.n_queued)
        m.gauge("serve/active_slots").set(self.n_active)
        # per-group minimum: Gauge.min is the run's free-page watermark,
        # the admission-control headroom signal
        m.gauge("serve/free_pages").set(free_min)
        m.counter("serve/inserts").inc(inserted)
        m.counter("serve/evictions").inc(evicted)
        m.counter("serve/tokens").inc(tokens)
        if prefill_tokens:
            # per-tick prefill compute: under chunked prefill its max is
            # bounded by chunk * slots — the p99-bounding claim as a
            # live histogram rather than a hope
            m.histogram("serve/prefill_tokens_tick").observe(prefill_tokens)
        if self.scfg.spec_k > 0:
            m.counter("serve/accepted").inc(accepted)
        m.gauge("serve/decode_compiles").set(self.decode_counter.count)
        m.gauge("serve/prefill_compiles").set(self.prefill_counter.count)
        if self.sink.enabled:  # skip the event build on the no-obs path
            self.sink.emit(
                "serve/tick",
                tick=self._tick, tick_s=round(tick_s, 6),
                queue_depth=self.n_queued, active=self.n_active,
                free_pages_min=free_min,
                inserted=inserted, evicted=evicted, tokens=tokens,
                accepted=accepted, prefill_tokens=prefill_tokens,
                decode_compiles=self.decode_counter.count,
                prefill_compiles=self.prefill_counter.count,
            )

    def _tick_inner(self) -> list[tuple[int, tuple[int, ...]]]:
        finished = []
        while self._queue:
            slot = self._find_slot(self._queue[0])
            if slot is None:
                break
            req = self._queue.popleft()
            if not self._admit(req, slot, finished):
                continue  # quarantined: the slot stays free
            st = self._slots[slot]
            # budget spent at prefill (an admission that already drained
            # its pending tail and emitted its one token); a chunked
            # admission still prefilling is evicted by _ctx_step later
            if (st is not None and not st.pending and st.generated
                    and req.max_new == 1):
                finished.append(self._evict(slot))

        # chunked prefill interleaves with decode INSIDE the tick: every
        # prefilling slot advances one chunk, every decoding slot one
        # token — a long admission costs each tick at most chunk tokens
        # of prefill instead of its whole prompt, which is what bounds
        # the resident streams' per-token p99
        prefilling = [s for s, st in enumerate(self._slots)
                      if st is not None and st.pending]
        if prefilling:
            self._ctx_step(prefilling, finished)
        active = [s for s, st in enumerate(self._slots)
                  if st is not None and not st.pending and st.generated]
        if not active:
            return finished
        if self.scfg.spec_k > 0:
            self._spec_tick(active, finished)
        else:
            self._decode_tick(active, finished)
        return finished

    def _decode_tick(self, active: list[int],
                     finished: list[tuple[int, tuple[int, ...]]]) -> None:
        """One plain decode sweep: one token per active slot."""
        scfg, geom = self.scfg, self.geom
        n = scfg.n_slots
        x = np.zeros((n, self.cfg.d_model), np.float32)
        tables = np.full((n, scfg.max_pages), geom.n_pages, np.int32)
        write_page = np.full((n,), geom.n_pages, np.int32)
        write_off = np.zeros((n,), np.int32)
        seq_lens = np.zeros((n,), np.int32)
        # idle slots keep (rid 0, pos 0): any key works, the draw is
        # discarded; one vectorized fold (request_keys) replaces ~3 tiny
        # dispatches per slot inside the latency-measured tick
        rids = np.zeros((n,), np.int32)
        positions = np.zeros((n,), np.int32)
        for s in active:
            st = self._slots[s]
            if self._tries is not None:  # CoW guard on the write target
                self._ensure_private(s, st.n_cached // geom.page_size)
            x[s] = self._embed_np[st.last_token]
            tables[s, : len(st.pages)] = st.pages
            write_page[s] = st.pages[st.n_cached // geom.page_size]
            write_off[s] = st.n_cached % geom.page_size
            seq_lens[s] = st.n_cached + 1
            rids[s] = st.rid
            positions[s] = len(st.generated)
        try:
            with self.timeline.span("serve/decode"):
                out, self._kv = self._decode(
                    self.params, self._kv, jnp.asarray(x), jnp.asarray(tables),
                    jnp.asarray(write_page), jnp.asarray(write_off),
                    jnp.asarray(seq_lens),
                )
                keys = request_keys(self._seed_key, jnp.asarray(rids),
                                    jnp.asarray(positions))
                logits = self._unembed(out, self.embed)
                toks = np.asarray(self._sample(keys, logits))
        except Exception:
            self._recover_cache()  # donated kv may be consumed; replay
            raise
        self._decode_s += self._last_span_s()
        self._decode_steps += 1
        self._slot_steps += len(active)
        self._fresh_tokens += len(active)
        for s in active:
            st = self._slots[s]
            st.n_cached += 1
            st.last_token = int(toks[s])
            st.generated.append(st.last_token)
            self._tokens_generated += 1
            if len(st.generated) >= st.max_new:
                finished.append(self._evict(s))

    def _spec_tick(self, active: list[int],
                   finished: list[tuple[int, tuple[int, ...]]]) -> None:
        """One speculative sweep: every active slot proposes up to
        ``spec_k`` self-drafted tokens (``propose_draft`` over its own
        prompt + generated history), the ONE verify forward scores the
        whole bank — each slot's cache pages gathered once for all its
        positions — and ``accept_speculative`` keeps the
        distribution-preserving prefix: ``a + 1`` tokens emitted per
        slot per sweep (``a`` accepted drafts + the terminal token),
        against ONE cache sweep instead of ``a + 1``.

        Rejected positions leave K/V garbage past the accepted frontier;
        the length masks hide it and the next sweep's writes (which
        start at the frontier and always cover at least as far)
        overwrite it — so speculation never dirties replayable state.
        The draft is clamped to the slot's remaining budget, keeping the
        page-footprint reservation made at admission valid."""
        scfg, geom = self.scfg, self.geom
        n, k = scfg.n_slots, scfg.spec_k
        K = k + 1
        x = np.zeros((n, K, self.cfg.d_model), np.float32)
        tables = np.full((n, scfg.max_pages), geom.n_pages, np.int32)
        write_pages = np.full((n, K), geom.n_pages, np.int32)
        write_offs = np.zeros((n, K), np.int32)
        seq_lens = np.zeros((n,), np.int32)
        drafts: dict[int, tuple[int, ...]] = {}
        for s in active:
            st = self._slots[s]
            remaining = st.max_new - len(st.generated)
            draft = propose_draft(
                st.prompt + tuple(st.generated), k, scfg.spec_ngram
            )[: remaining - 1]
            drafts[s] = draft
            toks = (st.last_token,) + draft
            if self._tries is not None:  # CoW guard on the write targets
                for pi in range(st.n_cached // geom.page_size,
                                (st.n_cached + len(toks) - 1)
                                // geom.page_size + 1):
                    self._ensure_private(s, pi)
            x[s, : len(toks)] = self._embed_np[list(toks)]
            tables[s, : len(st.pages)] = st.pages
            for j in range(len(toks)):
                pos = st.n_cached + j
                write_pages[s, j] = st.pages[pos // geom.page_size]
                write_offs[s, j] = pos % geom.page_size
            seq_lens[s] = st.n_cached + 1
        try:
            with self.timeline.span("serve/decode"):
                out, self._kv = self._decode(
                    self.params, self._kv, jnp.asarray(x), jnp.asarray(tables),
                    jnp.asarray(write_pages), jnp.asarray(write_offs),
                    jnp.asarray(seq_lens),
                )
                logits = np.asarray(self._unembed(out, self.embed))
        except Exception:
            self._recover_cache()  # donated kv may be consumed; replay
            raise
        self._decode_s += self._last_span_s()
        self._decode_steps += 1
        self._slot_steps += len(active)
        accept_hist = self.metrics.histogram("serve/accept_len")
        for s in active:
            st = self._slots[s]
            a, toks = accept_speculative(
                scfg.seed, st.rid, len(st.generated), logits[s], drafts[s],
                scfg.temperature, scfg.top_k,
            )
            accept_hist.observe(a)
            self._spec_drafted += len(drafts[s])
            self._spec_accepted += a
            self._fresh_tokens += a + 1
            st.n_cached += a + 1
            st.generated.extend(toks)
            st.last_token = toks[-1]
            self._tokens_generated += len(toks)
            if len(st.generated) >= st.max_new:
                finished.append(self._evict(s))

    def run(self, requests: Sequence[Request] = (),
            max_steps: int = 100_000) -> GenerateReport:
        """Submit ``requests`` and drain queue + slots to empty.  Counters
        in the report are THIS drain's deltas (compile counts stay
        engine-lifetime: that is what 'zero steady-state recompiles'
        means), so a reused engine's reports stay internally consistent
        — tokens_generated always reconciles with this run's outputs
        plus any requests already in flight at entry."""
        tokens0 = self._tokens_generated
        decode0, prefill0 = self._decode_steps, self._prefill_count
        prefill_s0, decode_s0 = self._prefill_s, self._decode_s
        slot0, drafted0 = self._slot_steps, self._spec_drafted
        accepted0 = self._spec_accepted
        ptok0, stok0 = self._prefill_tokens, self._shared_tokens
        fresh0, cow0 = self._fresh_tokens, self._cow_pages
        quarantined0 = set(self._quarantined)
        for r in requests:
            self.submit(r)
        outputs: dict[int, tuple[int, ...]] = {}
        steps = 0
        while self._queue or self.n_active:
            if steps >= max_steps:
                raise RuntimeError(
                    f"engine did not drain in {max_steps} steps "
                    f"({self.n_queued} queued, {self.n_active} active)"
                )
            for rid, toks in self.step():
                outputs[rid] = toks
            steps += 1
        report = self._report(outputs, tokens0, decode0, prefill0,
                              prefill_s0, decode_s0, slot0, drafted0,
                              accepted0,
                              tuple(sorted(set(self._quarantined)
                                           - quarantined0)),
                              ptok0, stok0, fresh0, cow0)
        self.sink.emit(
            "serve/report",
            completed=report.completed,
            tokens_generated=report.tokens_generated,
            decode_steps=report.decode_steps, prefills=report.prefills,
            decode_compiles=report.decode_compiles,
            prefill_compiles=report.prefill_compiles,
            prefill_s=round(report.prefill_s, 6),
            decode_s=round(report.decode_s, 6),
            quarantined=len(report.quarantined),
            slot_steps=report.slot_steps,
            drafted=report.drafted, accepted=report.accepted,
            prefill_tokens=report.prefill_tokens,
            shared_tokens=report.shared_tokens,
            cow_pages=report.cow_pages,
            fresh_kv_bytes=round(report.fresh_kv_bytes, 3),
        )
        emit_phase_totals(self.sink, self.recorder)
        self.sink.emit_metrics(self.metrics.snapshot(),
                               scope=self.metrics.id)
        self.sink.flush()
        return report

    def _report(self, outputs, tokens0, decode0, prefill0, prefill_s0,
                decode_s0, slot0=0, drafted0=0, accepted0=0,
                quarantined=(), ptok0=0, stok0=0, fresh0=0,
                cow0=0) -> GenerateReport:
        return GenerateReport(
            completed=len(outputs),
            tokens_generated=self._tokens_generated - tokens0,
            decode_steps=self._decode_steps - decode0,
            prefills=self._prefill_count - prefill0,
            decode_compiles=self.decode_compiles,
            prefill_compiles=self.prefill_compiles,
            prefill_s=self._prefill_s - prefill_s0,
            decode_s=self._decode_s - decode_s0,
            outputs=tuple(sorted(outputs.items())),
            quarantined=tuple(quarantined),
            slot_steps=self._slot_steps - slot0,
            drafted=self._spec_drafted - drafted0,
            accepted=self._spec_accepted - accepted0,
            prefill_tokens=self._prefill_tokens - ptok0,
            shared_tokens=self._shared_tokens - stok0,
            cow_pages=self._cow_pages - cow0,
            fresh_kv_bytes=(self._fresh_tokens - fresh0)
            * self.kv_bytes_per_token,
        )
