"""Continuous-batching generation engine over the sharded decode stack.

The Orca (OSDI '22) scheduling idea on this framework's mesh: a fixed
bank of decode slots runs one compiled single-token step per tick, and
requests are inserted into / evicted from slots BETWEEN ticks — a
finishing sequence hands its slot and pages to the next queued request
at the next step boundary instead of holding the batch hostage until
the longest member drains.  Admission is a free-page watermark: a
request enters only when its slot's data-parallel group can cover the
request's WHOLE page footprint (prompt + budgeted new tokens), so a
running sequence can never hit page exhaustion mid-stream.

Everything compiled is shape-stable by construction — the decode step
always sees all ``n_slots`` slots (idle ones masked by ``seq_len == 0``
and sentinel page ids), prompts pad to power-of-two length buckets — so
steady-state serving triggers ZERO recompiles after warmup, asserted
through the :class:`~tpuscratch.serve.decode.CompileCounter` hooks.
Scheduling itself is host-side Python between compiled steps, the same
layering as the reference's rank-0 driver loops.

``GenerateReport`` mirrors ``models/trainer.TrainReport``; prefill and
decode are bracketed by ``runtime.profiling.Timeline`` spans, pulled
into the report as aggregate seconds.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tpuscratch.ft.chaos import bind_sink
from tpuscratch.models.transformer import TransformerConfig, init_params
from tpuscratch.obs.metrics import CompileCounter, MetricsRegistry
from tpuscratch.obs.sink import NullSink
from tpuscratch.obs.trace import FlightRecorder, emit_phase_totals
from tpuscratch.runtime.profiling import Timeline
from tpuscratch.serve.decode import (
    build_decode_step,
    build_prefill,
    build_verify_step,
    check_serve_mesh,
    propose_draft,
)
from tpuscratch.serve.kvcache import CacheGeometry, PageAllocator, init_kv_cache
from tpuscratch.serve.sampling import (
    accept_speculative,
    request_key,
    request_keys,
    sample_batch,
)

#: ServeConfig.kv_dtype spellings -> cache buffer dtype
_KV_DTYPES = {"float32": jnp.float32, "int8": jnp.int8}


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (the model itself comes from ``TransformerConfig``)."""

    n_slots: int = 8          # fixed decode-batch width (all dp groups)
    n_pages: int = 64         # KV pages PER dp group
    page_size: int = 8        # tokens per page
    max_seq: int = 64         # per-request prompt + generated cap
    vocab: int = 32           # token-id space (tied embed/unembed)
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0            # 0 = full distribution
    seed: int = 0             # sampling + embedding seed
    # extra prefill attempts per request before QUARANTINE.  0 (default)
    # keeps the legacy contract: a failed admission requeues the request
    # and re-raises to the caller.  > 0: failed admissions are retried
    # in-engine (transient faults complete) and a request that exhausts
    # the budget is quarantined — reported, never requeued — so one
    # poison request cannot livelock the engine.
    retry_budget: int = 0
    # cache-byte lever: "float32" (exact) or "int8" (pages quantized
    # with per-page per-head scales — ~4x fewer cache bytes per token,
    # the decode gather's roofline; see serve/kvcache.py)
    kv_dtype: str = "float32"
    # HBM-sweep-amortization lever: draft tokens scored per verify sweep
    # (0 = speculation off).  > 0 replaces the one-token decode program
    # with ONE (spec_k + 1)-token verify program; accepted prefixes emit
    # up to spec_k + 1 tokens per cache sweep, and the acceptance rule
    # preserves the sampling distribution exactly (bit-identical output
    # under greedy; serve/sampling.accept_speculative)
    spec_k: int = 0
    # suffix length for the self-drafting prompt-lookup match
    spec_ngram: int = 2

    @property
    def max_pages(self) -> int:
        """Page-table width: the per-request page footprint ceiling."""
        return -(-self.max_seq // self.page_size)


@dataclasses.dataclass(frozen=True)
class Request:
    rid: int                  # unique per engine (keys the PRNG stream)
    prompt: tuple[int, ...]   # token ids
    max_new: int              # generation budget (>= 1)


@dataclasses.dataclass(frozen=True)
class GenerateReport:
    """What a drain produced — the serving twin of ``TrainReport``.

    Speculative accounting reconciles by construction:
    ``tokens_generated == prefills + slot_steps + accepted`` — every
    emitted token is a prefill token, a verify sweep's base token (one
    per active slot per tick, speculation on or off), or an accepted
    draft token (ex24 asserts this identity on a live run)."""

    completed: int
    tokens_generated: int
    decode_steps: int
    prefills: int
    decode_compiles: int
    prefill_compiles: int
    prefill_s: float
    decode_s: float
    outputs: tuple[tuple[int, tuple[int, ...]], ...]  # (rid, tokens) by rid
    quarantined: tuple[int, ...] = ()  # rids dropped THIS drain (budget spent)
    slot_steps: int = 0   # active-slot decode/verify invocations
    drafted: int = 0      # speculative draft tokens scored
    accepted: int = 0     # draft tokens accepted into outputs

    @property
    def accept_len_mean(self) -> Optional[float]:
        """Mean accepted draft length per verify sweep (None: no sweeps)."""
        if self.slot_steps == 0:
            return None
        return self.accepted / self.slot_steps


@dataclasses.dataclass
class _Slot:
    rid: int
    prompt: tuple[int, ...]   # kept for deterministic replay on recovery
    pages: list[int]          # LOCAL page ids in this slot's group
    n_cached: int             # tokens whose K/V are in the cache
    max_new: int
    last_token: int
    generated: list[int]


#: profiling spans kept on the engine's Timeline — a recent window, not
#: engine-lifetime history (a continuously-serving engine would otherwise
#: grow one Span per tick without bound)
_MAX_SPANS = 1024


def init_embed(seed: int, vocab: int, d_model: int) -> jax.Array:
    """Tied token embedding / unembedding table (V, d)."""
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.standard_normal((vocab, d_model)).astype(np.float32)
        / np.sqrt(d_model)
    )


def _bucket(n: int) -> int:
    """Prompt shape bucket: next power of two, floor 8 — bounds prefill
    compiles at log2(max_seq) programs."""
    b = 8
    while b < n:
        b *= 2
    return b


class ServeEngine:
    """Sharded continuous-batching engine.  ``submit`` queues requests,
    ``step`` runs one admission + decode tick, ``run`` drains.

    Slot ``s`` belongs to dp group ``s // (n_slots / dp_size)`` — the
    contiguous chunk P(dp) sharding hands that group — and its pages come
    from that group's own :class:`PageAllocator` (ids are group-local,
    matching the dp-sharded pages axis of the cache).

    ``sink`` (an ``obs.sink.Sink``; default the no-op ``NullSink``)
    receives one ``serve/tick`` event per tick plus a ``serve/report`` +
    metrics snapshot per drain; ``self.metrics`` is the live
    ``obs.MetricsRegistry`` regardless of sink.  ``recorder`` (an
    ``obs.trace.FlightRecorder``; a fresh bounded one when absent — the
    flight recorder is always on) collects the prefill/decode spans via
    the engine's Timeline for Chrome-trace export; per-phase totals are
    emitted as cumulative ``trace/phase`` events at each drain."""

    def __init__(self, mesh: Mesh, cfg: TransformerConfig, scfg: ServeConfig,
                 params: Optional[dict] = None,
                 embed: Optional[jax.Array] = None,
                 dp: str = "dp", sp: str = "sp",
                 sink=None, chaos=None, recorder=None):
        check_serve_mesh(mesh, cfg, dp, sp)
        self._dp_size = mesh.shape[dp]
        if scfg.n_slots % self._dp_size:
            raise ValueError(
                f"n_slots {scfg.n_slots} not divisible by dp size "
                f"{self._dp_size}"
            )
        if scfg.max_seq > scfg.n_pages * scfg.page_size:
            raise ValueError(
                f"max_seq {scfg.max_seq} exceeds one group's pool "
                f"({scfg.n_pages} pages x {scfg.page_size})"
            )
        if scfg.kv_dtype not in _KV_DTYPES:
            raise ValueError(
                f"kv_dtype {scfg.kv_dtype!r} not in {sorted(_KV_DTYPES)}"
            )
        if scfg.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {scfg.spec_k}")
        if scfg.spec_ngram < 1:
            raise ValueError(
                f"spec_ngram must be >= 1, got {scfg.spec_ngram}"
            )
        self.mesh, self.cfg, self.scfg = mesh, cfg, scfg
        self._kv_jnp_dtype = _KV_DTYPES[scfg.kv_dtype]
        self._quantized = scfg.kv_dtype == "int8"
        self.geom = CacheGeometry(
            cfg.n_layers, scfg.n_pages, scfg.page_size, cfg.n_heads,
            cfg.d_head,
        )
        self.params = (
            params if params is not None else init_params(scfg.seed, cfg)
        )
        self.embed = (
            embed if embed is not None
            else init_embed(scfg.seed, scfg.vocab, cfg.d_model)
        )
        if self.embed.shape != (scfg.vocab, cfg.d_model):
            raise ValueError(
                f"embed {self.embed.shape} != ({scfg.vocab}, {cfg.d_model})"
            )
        self._embed_np = np.asarray(self.embed)
        self._kv = init_kv_cache(self.geom, self._dp_size,
                                 self._kv_jnp_dtype)
        self._allocators = [
            PageAllocator(scfg.n_pages) for _ in range(self._dp_size)
        ]
        self._slots: list[Optional[_Slot]] = [None] * scfg.n_slots
        self._slots_per_group = scfg.n_slots // self._dp_size
        self._queue: collections.deque[Request] = collections.deque()
        self._seen_rids: set[int] = set()
        self._chaos = chaos  # ft.ChaosPlan or None: "serve/prefill" site
        self._quarantined: dict[int, str] = {}  # rid -> last error
        self._seed_key = jax.random.key(scfg.seed)
        self.recorder = (
            recorder if recorder is not None else FlightRecorder()
        )
        self.timeline = Timeline(self.recorder)
        # observability: every tick updates the registry (host-side
        # attribute writes, < 2% of a compiled step) and, when a sink is
        # attached, emits one JSONL event — queue depth, free-page
        # watermark, tick latency, insert/evict counts, compile counts
        self.metrics = MetricsRegistry()
        self.sink = sink if sink is not None else NullSink()
        bind_sink(chaos, self.sink)  # injected ft/fault events join the stream
        self._tick = 0
        self.sink.emit(
            "serve/engine",
            n_slots=scfg.n_slots, n_pages=scfg.n_pages,
            page_size=scfg.page_size, max_seq=scfg.max_seq,
            dp_size=self._dp_size, n_layers=cfg.n_layers,
            n_heads=cfg.n_heads, d_model=cfg.d_model,
            kv_dtype=scfg.kv_dtype, spec_k=scfg.spec_k,
        )
        self.decode_counter = CompileCounter()
        self.prefill_counter = CompileCounter()
        # speculation swaps the one-token decode program for ONE fixed
        # (spec_k + 1)-token verify program — still a single compile,
        # still counted by decode_counter
        if scfg.spec_k > 0:
            self._decode = build_verify_step(
                mesh, cfg, self.geom, scfg.spec_k, dp=dp, sp=sp,
                counter=self.decode_counter, quantized=self._quantized,
            )
        else:
            self._decode = build_decode_step(
                mesh, cfg, self.geom, dp=dp, sp=sp,
                counter=self.decode_counter, quantized=self._quantized,
            )
        self._prefills: dict[int, object] = {}  # bucket len -> program
        self._dp, self._sp = dp, sp
        self._unembed = jax.jit(lambda o, e: o @ e.T)
        self._decode_steps = 0
        self._prefill_count = 0
        self._tokens_generated = 0
        self._slot_steps = 0
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._prefill_s = 0.0
        self._decode_s = 0.0

    # ---- introspection (tests + report) --------------------------------

    @property
    def decode_compiles(self) -> int:
        return self.decode_counter.count

    @property
    def prefill_compiles(self) -> int:
        return self.prefill_counter.count

    def free_pages(self) -> list[int]:
        """Per-group free-page counts (the leak check reads this)."""
        return [a.n_free for a in self._allocators]

    @property
    def kv_cache_bytes(self) -> int:
        """Total cache-pool bytes (pages + quantization scales) — the
        static quantity the int8 lever shrinks; ``obs.ledger`` does the
        accounting so bench rows and regression tests share it."""
        from tpuscratch.obs.ledger import kv_cache_bytes

        return kv_cache_bytes(self._kv)

    @property
    def kv_bytes_per_token(self) -> float:
        """Cache bytes per token of pool capacity (pages + scales over
        ``dp_size * n_pages * page_size`` token slots)."""
        return self.kv_cache_bytes / (self._dp_size * self.geom.max_tokens)

    @property
    def tokens_generated(self) -> int:
        """Engine-lifetime emitted tokens (benches read deltas)."""
        return self._tokens_generated

    @property
    def slot_steps(self) -> int:
        """Engine-lifetime active-slot decode/verify invocations."""
        return self._slot_steps

    @property
    def spec_drafted(self) -> int:
        return self._spec_drafted

    @property
    def spec_accepted(self) -> int:
        return self._spec_accepted

    @property
    def n_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def n_queued(self) -> int:
        return len(self._queue)

    @property
    def quarantined(self) -> dict[int, str]:
        """{rid: last error} of requests dropped after the retry budget."""
        return dict(self._quarantined)

    def _group_of(self, slot: int) -> int:
        return slot // self._slots_per_group

    def _last_span_s(self) -> float:
        """Seconds of the span just recorded; trims the Timeline to a
        recent window so a long-lived engine's span list stays bounded."""
        s = self.timeline.spans[-1].seconds
        if len(self.timeline.spans) > _MAX_SPANS:
            del self.timeline.spans[: -_MAX_SPANS]
        return s

    def _recover_cache(self) -> None:
        """A compiled call raised mid-flight: its DONATED cache buffers
        may already be consumed, so serving cannot continue on the old
        pool.  Reset it and requeue every in-flight request from its
        original prompt — rids key the PRNG streams, so the replay
        regenerates the SAME tokens and a caller that catches the error
        and drains again loses nothing."""
        for s, st in enumerate(self._slots):
            if st is None:
                continue
            self._allocators[self._group_of(s)].free(st.pages)
            self._slots[s] = None
            self._queue.appendleft(
                Request(rid=st.rid, prompt=st.prompt, max_new=st.max_new)
            )
        self._kv = init_kv_cache(self.geom, self._dp_size,
                                 self._kv_jnp_dtype)

    # ---- request lifecycle ---------------------------------------------

    def submit(self, req: Request) -> None:
        if req.max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {req.max_new}")
        if req.rid < 0:
            raise ValueError(f"rid must be >= 0, got {req.rid}")
        if not req.prompt:
            raise ValueError("empty prompt")
        if len(req.prompt) + req.max_new > self.scfg.max_seq:
            raise ValueError(
                f"request {req.rid}: prompt {len(req.prompt)} + max_new "
                f"{req.max_new} exceeds max_seq {self.scfg.max_seq}"
            )
        if any(t < 0 or t >= self.scfg.vocab for t in req.prompt):
            raise ValueError(f"request {req.rid}: token id out of vocab")
        if req.rid in self._seen_rids:
            # rids key the PRNG streams AND the report's outputs map — a
            # reuse would silently drop one output and sample identical
            # token streams for both
            raise ValueError(f"request id {req.rid} already used")
        self._seen_rids.add(req.rid)
        self._queue.append(req)

    def _find_slot(self, req: Request) -> Optional[int]:
        need = self.geom.pages_for(len(req.prompt) + req.max_new)
        for s, slot in enumerate(self._slots):
            if slot is None and (
                self._allocators[self._group_of(s)].n_free >= need
            ):
                return s
        return None

    def _sample(self, keys, logits):
        return sample_batch(
            keys, logits, self.scfg.temperature, self.scfg.top_k
        )

    def _admit(self, req: Request, slot: int) -> bool:
        """Prefill ``req`` into ``slot``; True when the slot was taken.

        With ``scfg.retry_budget == 0`` (default) a prefill failure keeps
        the legacy contract: grant returned, request requeued at the
        head, cache recovered, exception re-raised.  With a budget,
        failed attempts are retried in-engine (the cache reset + replay
        between attempts, so transient faults complete with outputs
        byte-identical to a fault-free run) and a request that exhausts
        ``1 + retry_budget`` attempts is QUARANTINED: its grant is
        returned, it never requeues, and the engine moves on — the
        deterministic-poison livelock the unconditional requeue had."""
        geom, scfg = self.geom, self.scfg
        group = self._group_of(slot)
        pages = self._allocators[group].alloc(
            geom.pages_for(len(req.prompt) + req.max_new)
        )
        assert pages is not None  # _find_slot checked the watermark
        n_tok = len(req.prompt)
        bucket = _bucket(n_tok)
        if bucket not in self._prefills:
            self._prefills[bucket] = build_prefill(
                self.mesh, self.cfg, geom, dp=self._dp, sp=self._sp,
                counter=self.prefill_counter, quantized=self._quantized,
            )
        x = np.zeros((bucket, self.cfg.d_model), np.float32)
        x[:n_tok] = self._embed_np[list(req.prompt)]
        page_rows = np.full(
            (self._dp_size, scfg.max_pages), geom.n_pages, np.int32
        )
        page_rows[group, : len(pages)] = pages

        def attempt() -> int:
            if self._chaos is not None:
                self._chaos.maybe_fail("serve/prefill", key=req.rid,
                                       op="serve/prefill")
            with self.timeline.span("serve/prefill"):
                out, self._kv = self._prefills[bucket](
                    self.params, self._kv, jnp.asarray(x),
                    jnp.asarray(page_rows), jnp.int32(n_tok),
                )
                logits = self._unembed(out[n_tok - 1][None], self.embed)
                return int(
                    self._sample(
                        request_key(scfg.seed, req.rid, 0)[None], logits
                    )[0]
                )

        if scfg.retry_budget == 0:
            try:
                tok = attempt()
            except Exception:
                # a failing prefill (transient device error, first-bucket
                # compile OOM) must not bleed the pool dry across retries:
                # return the grant, put the request back at the head, and
                # reset the (possibly donated-and-consumed) cache — every
                # in-flight request requeues for deterministic replay
                self._allocators[group].free(pages)
                self._queue.appendleft(req)
                self._recover_cache()
                raise
        else:
            tok = None
            attempts = 1 + scfg.retry_budget
            for a in range(attempts):
                try:
                    tok = attempt()
                    break
                except Exception as exc:
                    self.metrics.counter("serve/prefill_failures").inc()
                    # the donated cache may be consumed: reset it and
                    # requeue every IN-FLIGHT request (rids key the PRNG
                    # streams, so their replay is byte-identical); THIS
                    # request keeps its grant for the next attempt
                    self._recover_cache()
                    if a + 1 >= attempts:
                        self._allocators[group].free(pages)
                        reason = f"{type(exc).__name__}: {exc}"
                        self._quarantined[req.rid] = reason
                        self.metrics.counter("serve/quarantined").inc()
                        self.sink.emit("ft/quarantine", rid=req.rid,
                                       attempts=attempts, error=reason)
                        return False
                    if self.sink.enabled:
                        self.sink.emit("ft/prefill_retry", rid=req.rid,
                                       attempt=a + 1,
                                       error=f"{type(exc).__name__}: {exc}")
        self._prefill_s += self._last_span_s()
        self._prefill_count += 1
        self._tokens_generated += 1
        self._slots[slot] = _Slot(
            rid=req.rid, prompt=req.prompt, pages=pages, n_cached=n_tok,
            max_new=req.max_new, last_token=tok, generated=[tok],
        )
        return True

    def _evict(self, slot: int) -> tuple[int, tuple[int, ...]]:
        st = self._slots[slot]
        assert st is not None
        self._allocators[self._group_of(slot)].free(st.pages)
        self._slots[slot] = None
        return st.rid, tuple(st.generated)

    # ---- the tick ------------------------------------------------------

    def step(self) -> list[tuple[int, tuple[int, ...]]]:
        """One engine tick: admit what fits, decode one token for every
        active slot, evict what finished.  Returns the finished
        ``(rid, tokens)`` pairs.  Each tick updates ``self.metrics``
        (tick latency, queue depth, free-page watermark, insert/evict
        counts, compile counts) and emits one sink event."""
        t0 = time.perf_counter()
        prefills0 = self._prefill_count
        tokens0 = self._tokens_generated
        accepted0 = self._spec_accepted
        finished = self._tick_inner()
        self._observe_tick(
            time.perf_counter() - t0,
            inserted=self._prefill_count - prefills0,
            evicted=len(finished),
            tokens=self._tokens_generated - tokens0,
            accepted=self._spec_accepted - accepted0,
        )
        return finished

    def _observe_tick(self, tick_s: float, inserted: int, evicted: int,
                      tokens: int, accepted: int = 0) -> None:
        m = self.metrics
        self._tick += 1
        free_min = min(a.n_free for a in self._allocators)
        m.histogram("serve/tick_s").observe(tick_s)
        m.gauge("serve/queue_depth").set(self.n_queued)
        m.gauge("serve/active_slots").set(self.n_active)
        # per-group minimum: Gauge.min is the run's free-page watermark,
        # the admission-control headroom signal
        m.gauge("serve/free_pages").set(free_min)
        m.counter("serve/inserts").inc(inserted)
        m.counter("serve/evictions").inc(evicted)
        m.counter("serve/tokens").inc(tokens)
        if self.scfg.spec_k > 0:
            m.counter("serve/accepted").inc(accepted)
        m.gauge("serve/decode_compiles").set(self.decode_counter.count)
        m.gauge("serve/prefill_compiles").set(self.prefill_counter.count)
        if self.sink.enabled:  # skip the event build on the no-obs path
            self.sink.emit(
                "serve/tick",
                tick=self._tick, tick_s=round(tick_s, 6),
                queue_depth=self.n_queued, active=self.n_active,
                free_pages_min=free_min,
                inserted=inserted, evicted=evicted, tokens=tokens,
                accepted=accepted,
                decode_compiles=self.decode_counter.count,
                prefill_compiles=self.prefill_counter.count,
            )

    def _tick_inner(self) -> list[tuple[int, tuple[int, ...]]]:
        finished = []
        while self._queue:
            slot = self._find_slot(self._queue[0])
            if slot is None:
                break
            req = self._queue.popleft()
            if not self._admit(req, slot):
                continue  # quarantined: the slot stays free
            if req.max_new == 1:
                finished.append(self._evict(slot))  # budget spent at prefill

        active = [s for s, st in enumerate(self._slots) if st is not None]
        if not active:
            return finished
        if self.scfg.spec_k > 0:
            self._spec_tick(active, finished)
        else:
            self._decode_tick(active, finished)
        return finished

    def _decode_tick(self, active: list[int],
                     finished: list[tuple[int, tuple[int, ...]]]) -> None:
        """One plain decode sweep: one token per active slot."""
        scfg, geom = self.scfg, self.geom
        n = scfg.n_slots
        x = np.zeros((n, self.cfg.d_model), np.float32)
        tables = np.full((n, scfg.max_pages), geom.n_pages, np.int32)
        write_page = np.full((n,), geom.n_pages, np.int32)
        write_off = np.zeros((n,), np.int32)
        seq_lens = np.zeros((n,), np.int32)
        # idle slots keep (rid 0, pos 0): any key works, the draw is
        # discarded; one vectorized fold (request_keys) replaces ~3 tiny
        # dispatches per slot inside the latency-measured tick
        rids = np.zeros((n,), np.int32)
        positions = np.zeros((n,), np.int32)
        for s in active:
            st = self._slots[s]
            x[s] = self._embed_np[st.last_token]
            tables[s, : len(st.pages)] = st.pages
            write_page[s] = st.pages[st.n_cached // geom.page_size]
            write_off[s] = st.n_cached % geom.page_size
            seq_lens[s] = st.n_cached + 1
            rids[s] = st.rid
            positions[s] = len(st.generated)
        try:
            with self.timeline.span("serve/decode"):
                out, self._kv = self._decode(
                    self.params, self._kv, jnp.asarray(x), jnp.asarray(tables),
                    jnp.asarray(write_page), jnp.asarray(write_off),
                    jnp.asarray(seq_lens),
                )
                keys = request_keys(self._seed_key, jnp.asarray(rids),
                                    jnp.asarray(positions))
                logits = self._unembed(out, self.embed)
                toks = np.asarray(self._sample(keys, logits))
        except Exception:
            self._recover_cache()  # donated kv may be consumed; replay
            raise
        self._decode_s += self._last_span_s()
        self._decode_steps += 1
        self._slot_steps += len(active)
        for s in active:
            st = self._slots[s]
            st.n_cached += 1
            st.last_token = int(toks[s])
            st.generated.append(st.last_token)
            self._tokens_generated += 1
            if len(st.generated) >= st.max_new:
                finished.append(self._evict(s))

    def _spec_tick(self, active: list[int],
                   finished: list[tuple[int, tuple[int, ...]]]) -> None:
        """One speculative sweep: every active slot proposes up to
        ``spec_k`` self-drafted tokens (``propose_draft`` over its own
        prompt + generated history), the ONE verify forward scores the
        whole bank — each slot's cache pages gathered once for all its
        positions — and ``accept_speculative`` keeps the
        distribution-preserving prefix: ``a + 1`` tokens emitted per
        slot per sweep (``a`` accepted drafts + the terminal token),
        against ONE cache sweep instead of ``a + 1``.

        Rejected positions leave K/V garbage past the accepted frontier;
        the length masks hide it and the next sweep's writes (which
        start at the frontier and always cover at least as far)
        overwrite it — so speculation never dirties replayable state.
        The draft is clamped to the slot's remaining budget, keeping the
        page-footprint reservation made at admission valid."""
        scfg, geom = self.scfg, self.geom
        n, k = scfg.n_slots, scfg.spec_k
        K = k + 1
        x = np.zeros((n, K, self.cfg.d_model), np.float32)
        tables = np.full((n, scfg.max_pages), geom.n_pages, np.int32)
        write_pages = np.full((n, K), geom.n_pages, np.int32)
        write_offs = np.zeros((n, K), np.int32)
        seq_lens = np.zeros((n,), np.int32)
        drafts: dict[int, tuple[int, ...]] = {}
        for s in active:
            st = self._slots[s]
            remaining = st.max_new - len(st.generated)
            draft = propose_draft(
                st.prompt + tuple(st.generated), k, scfg.spec_ngram
            )[: remaining - 1]
            drafts[s] = draft
            toks = (st.last_token,) + draft
            x[s, : len(toks)] = self._embed_np[list(toks)]
            tables[s, : len(st.pages)] = st.pages
            for j in range(len(toks)):
                pos = st.n_cached + j
                write_pages[s, j] = st.pages[pos // geom.page_size]
                write_offs[s, j] = pos % geom.page_size
            seq_lens[s] = st.n_cached + 1
        try:
            with self.timeline.span("serve/decode"):
                out, self._kv = self._decode(
                    self.params, self._kv, jnp.asarray(x), jnp.asarray(tables),
                    jnp.asarray(write_pages), jnp.asarray(write_offs),
                    jnp.asarray(seq_lens),
                )
                logits = np.asarray(self._unembed(out, self.embed))
        except Exception:
            self._recover_cache()  # donated kv may be consumed; replay
            raise
        self._decode_s += self._last_span_s()
        self._decode_steps += 1
        self._slot_steps += len(active)
        accept_hist = self.metrics.histogram("serve/accept_len")
        for s in active:
            st = self._slots[s]
            a, toks = accept_speculative(
                scfg.seed, st.rid, len(st.generated), logits[s], drafts[s],
                scfg.temperature, scfg.top_k,
            )
            accept_hist.observe(a)
            self._spec_drafted += len(drafts[s])
            self._spec_accepted += a
            st.n_cached += a + 1
            st.generated.extend(toks)
            st.last_token = toks[-1]
            self._tokens_generated += len(toks)
            if len(st.generated) >= st.max_new:
                finished.append(self._evict(s))

    def run(self, requests: Sequence[Request] = (),
            max_steps: int = 100_000) -> GenerateReport:
        """Submit ``requests`` and drain queue + slots to empty.  Counters
        in the report are THIS drain's deltas (compile counts stay
        engine-lifetime: that is what 'zero steady-state recompiles'
        means), so a reused engine's reports stay internally consistent
        — tokens_generated always reconciles with this run's outputs
        plus any requests already in flight at entry."""
        tokens0 = self._tokens_generated
        decode0, prefill0 = self._decode_steps, self._prefill_count
        prefill_s0, decode_s0 = self._prefill_s, self._decode_s
        slot0, drafted0 = self._slot_steps, self._spec_drafted
        accepted0 = self._spec_accepted
        quarantined0 = set(self._quarantined)
        for r in requests:
            self.submit(r)
        outputs: dict[int, tuple[int, ...]] = {}
        steps = 0
        while self._queue or self.n_active:
            if steps >= max_steps:
                raise RuntimeError(
                    f"engine did not drain in {max_steps} steps "
                    f"({self.n_queued} queued, {self.n_active} active)"
                )
            for rid, toks in self.step():
                outputs[rid] = toks
            steps += 1
        report = self._report(outputs, tokens0, decode0, prefill0,
                              prefill_s0, decode_s0, slot0, drafted0,
                              accepted0,
                              tuple(sorted(set(self._quarantined)
                                           - quarantined0)))
        self.sink.emit(
            "serve/report",
            completed=report.completed,
            tokens_generated=report.tokens_generated,
            decode_steps=report.decode_steps, prefills=report.prefills,
            decode_compiles=report.decode_compiles,
            prefill_compiles=report.prefill_compiles,
            prefill_s=round(report.prefill_s, 6),
            decode_s=round(report.decode_s, 6),
            quarantined=len(report.quarantined),
            slot_steps=report.slot_steps,
            drafted=report.drafted, accepted=report.accepted,
        )
        emit_phase_totals(self.sink, self.recorder)
        self.sink.emit_metrics(self.metrics.snapshot(),
                               scope=self.metrics.id)
        self.sink.flush()
        return report

    def _report(self, outputs, tokens0, decode0, prefill0, prefill_s0,
                decode_s0, slot0=0, drafted0=0, accepted0=0,
                quarantined=()) -> GenerateReport:
        return GenerateReport(
            completed=len(outputs),
            tokens_generated=self._tokens_generated - tokens0,
            decode_steps=self._decode_steps - decode0,
            prefills=self._prefill_count - prefill0,
            decode_compiles=self.decode_compiles,
            prefill_compiles=self.prefill_compiles,
            prefill_s=self._prefill_s - prefill_s0,
            decode_s=self._decode_s - decode_s0,
            outputs=tuple(sorted(outputs.items())),
            quarantined=tuple(quarantined),
            slot_steps=self._slot_steps - slot0,
            drafted=self._spec_drafted - drafted0,
            accepted=self._spec_accepted - accepted0,
        )
