"""Continuous-batching generation engine over the sharded decode stack.

The Orca (OSDI '22) scheduling idea on this framework's mesh: a fixed
bank of decode slots runs one compiled single-token step per tick, and
requests are inserted into / evicted from slots BETWEEN ticks — a
finishing sequence hands its slot and pages to the next queued request
at the next step boundary instead of holding the batch hostage until
the longest member drains.  Admission is a free-page watermark: a
request enters only when its slot's data-parallel group can cover the
request's WHOLE page footprint (prompt + budgeted new tokens), so a
running sequence can never hit page exhaustion mid-stream.

Everything compiled is shape-stable by construction — the decode step
always sees all ``n_slots`` slots (idle ones masked by ``seq_len == 0``
and sentinel page ids), prompts pad to power-of-two length buckets — so
steady-state serving triggers ZERO recompiles after warmup, asserted
through the :class:`~tpuscratch.serve.decode.CompileCounter` hooks.
Scheduling itself is host-side Python between compiled steps, the same
layering as the reference's rank-0 driver loops.

``GenerateReport`` mirrors ``models/trainer.TrainReport``; prefill and
decode are bracketed by ``runtime.profiling.Timeline`` spans, pulled
into the report as aggregate seconds.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from tpuscratch.ft.chaos import bind_sink, bind_tracer
from tpuscratch.ft.retry import RetryPolicy, retry as ft_retry
from tpuscratch.models.transformer import TransformerConfig, init_params
from tpuscratch.obs.metrics import CompileCounter, MetricsRegistry
from tpuscratch.obs.reqtrace import NullReqTracer
from tpuscratch.obs.sink import NullSink
from tpuscratch.obs.trace import FlightRecorder, emit_phase_totals
from tpuscratch.runtime.profiling import Timeline
from tpuscratch.serve.decode import (
    build_context_prefill,
    build_decode_loop,
    build_decode_step,
    build_prefill,
    build_spec_decode_loop,
    build_verify_step,
    check_serve_mesh,
    macro_occupancy,
    plan_sweep_waves,
    propose_draft,
)
from tpuscratch.serve.kvcache import (
    CacheGeometry,
    HostPageStore,
    HostTierError,
    PageAllocator,
    PrefixCache,
    ResidencyPolicy,
    TieredPageAllocator,
    host_leaf_shapes,
    init_kv_cache,
)
from tpuscratch.serve.sampling import (
    accept_speculative,
    request_key,
    request_keys,
    sample_batch,
)

#: ServeConfig.kv_dtype spellings -> cache buffer dtype (the fp32 /
#: int8 / fp8-e4m3 ladder; both quantized rungs carry scale planes)
_KV_DTYPES = {
    "float32": jnp.float32,
    "int8": jnp.int8,
    "fp8": jnp.float8_e4m3fn,
}

#: ServeConfig.fused_attention spellings -> the ops.attention ``fused``
#: argument ("auto" follows the backend policy: fused Pallas sweep on a
#: real TPU, dense XLA oracle elsewhere)
_FUSED_MODES = {"auto": None, "on": True, "off": False}


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (the model itself comes from ``TransformerConfig``)."""

    n_slots: int = 8          # fixed decode-batch width (all dp groups)
    n_pages: int = 64         # KV pages PER dp group
    page_size: int = 8        # tokens per page
    max_seq: int = 64         # per-request prompt + generated cap
    vocab: int = 32           # token-id space (tied embed/unembed)
    temperature: float = 0.0  # 0 = greedy
    top_k: int = 0            # 0 = full distribution
    seed: int = 0             # sampling + embedding seed
    # extra prefill attempts per request before QUARANTINE.  0 (default)
    # keeps the legacy contract: a failed admission requeues the request
    # and re-raises to the caller.  > 0: failed admissions are retried
    # in-engine (transient faults complete) and a request that exhausts
    # the budget is quarantined — reported, never requeued — so one
    # poison request cannot livelock the engine.
    retry_budget: int = 0
    # cache-byte lever: "float32" (exact), "int8", or "fp8" (e4m3) —
    # the quantized rungs store pages at one byte per element with
    # per-page per-head scales, ~4x fewer cache bytes per token (the
    # decode gather's roofline); fp8 is the accuracy-per-byte rung
    # (floating grid, outlier-robust) at the same bytes as int8.  See
    # serve/kvcache.py for the ladder table.
    kv_dtype: str = "float32"
    # decode-sweep kernel: "auto" (fused Pallas paged-attention kernel
    # on a real TPU, dense XLA oracle elsewhere), "on" (force fused —
    # interpret-mode Pallas off-TPU, the equivalence-test path), "off"
    # (force the dense oracle).  Applies to decode, speculative verify,
    # and chunked context prefill — the three paths share one kernel
    # family (ops.attention.paged_attention).
    fused_attention: str = "auto"
    # HBM-sweep-amortization lever: draft tokens scored per verify sweep
    # (0 = speculation off).  > 0 replaces the one-token decode program
    # with ONE (spec_k + 1)-token verify program; accepted prefixes emit
    # up to spec_k + 1 tokens per cache sweep, and the acceptance rule
    # preserves the sampling distribution exactly (bit-identical output
    # under greedy; serve/sampling.accept_speculative)
    spec_k: int = 0
    # suffix length for the self-drafting prompt-lookup match
    spec_ngram: int = 2
    # cross-request KV prefix sharing (off by default): admissions whose
    # prompts share a full-page-aligned prefix with LIVE cached pages
    # attach to them (allocator refcount +1) instead of re-prefilling —
    # only the unshared tail runs through the context-prefill program,
    # so prefill FLOPs and freshly-written KV bytes drop with the share
    # ratio; copy-on-write protects shared pages from in-place writes
    prefix_share: bool = False
    # chunked prefill (0 = off): prompts advance at most N tokens per
    # engine tick through the context-prefill program instead of paying
    # their whole length inside one tick — one long admission stops
    # blocking every resident decode stream (bounds per-token p99)
    chunk_prefill: int = 0
    # device-resident macro-step decode (ISSUE 15, clamps lifted by
    # ISSUE 19): tokens — token ROUNDS, under speculation — generated
    # per engine dispatch.  1 (default) runs the EXACT legacy per-token
    # program; N > 1 fuses N whole engine ticks — decode sweep,
    # unembed, sample, KV write, frontier/length advance — into ONE
    # compiled lax.scan carrying all slot state on device, so the
    # engine pays ONE XLA dispatch and ONE sampling host-sync per N
    # rounds instead of per round (the dominant un-attacked term on
    # the decode hot path once the sweep itself is cheap).  Greedy
    # output is bit-identical at any N; insert/evict/admission,
    # chunked-prefill advancement and router re-roling happen at
    # macro-tick boundaries; in-carry done/stop masks suppress writes
    # for slots whose budget or stop token ends them mid-scan and an
    # in-program early-exit mask skips the tail of an all-done bank.
    # COMPOSES with both former clamp paths (ISSUE 19): spec_k > 0
    # moves draft proposal + Leviathan accept/resample into the scan
    # carry (one dispatch covers up to N * (spec_k + 1) token rounds)
    # and kv_host_pages > 0 wave-partitions the macro scan with
    # next-wave prefetch behind the running dispatch; nothing clamps
    # (macro_steps_effective == macro_steps, macro_clamped_by None).
    macro_steps: int = 1
    # async macro tick (ISSUE 19, plain macro path): when the bank is
    # in pure steady-state decode — untiered, unspeculated, unshared,
    # empty queue, no prefilling slots, no stop tokens — chain ALL
    # remaining scans for the resident requests back-to-back on the
    # device-side final carry (budgets/stop state ride the scan
    # outputs), syncing their sampled tokens only after the last scan
    # is dispatched: the host never sits between consecutive scans.
    # Exact-continuation equivalent to one longer scan, so output and
    # the dispatch identity (dispatches == ceil(slot_steps / T)) are
    # unchanged; any condition above failing falls back to the
    # one-scan-per-tick path for that tick.
    async_macro: bool = False
    # tiered KV memory (0 = off): N host-tier page slots PER dp group
    # (serve/kvcache.HostPageStore over native/hostpool pinned buffers).
    # Cold pages — idle reserve tails, old chunks past the residency
    # horizon, evicted-but-shared prefix chains — spill to the host
    # tier and prefetch back AHEAD of the decode sweep (wave-scheduled,
    # double-buffered), so admission capacity becomes device + host
    # pages at fixed HBM while a warm-path decode tick never blocks on
    # a transfer; a cold hit falls back to a synchronous prefetch whose
    # cost is measured (serve/cold_hit_s).  Greedy output is
    # bit-identical with spilling forced on, across the dtype ladder
    # and composed with prefix-share / spec / chunked prefill / disagg.
    kv_host_pages: int = 0

    @property
    def max_pages(self) -> int:
        """Page-table width: the per-request page footprint ceiling."""
        return -(-self.max_seq // self.page_size)


@dataclasses.dataclass(frozen=True)
class Request:
    rid: int                  # unique per engine (keys the PRNG stream)
    prompt: tuple[int, ...]   # token ids
    max_new: int              # generation budget (>= 1)
    # per-request stop tokens (device-side EOS, ISSUE 19): generation
    # ends early when a sampled token is in this set — the stop token
    # itself IS emitted (it closes the output), then the slot finishes.
    # Checked in-carry on the macro paths (no host sync to decide) and
    # host-side on the per-token paths; () keeps the budget-only
    # contract byte-for-byte.
    stop_tokens: tuple[int, ...] = ()


@dataclasses.dataclass(frozen=True)
class GenerateReport:
    """What a drain produced — the serving twin of ``TrainReport``.

    Speculative accounting reconciles by construction:
    ``tokens_generated == prefills + slot_steps + accepted`` — every
    emitted token is a prefill token, a verify sweep's base token (one
    per active slot per tick, speculation on or off), or an accepted
    draft token (ex24 asserts this identity on a live run)."""

    completed: int
    tokens_generated: int
    decode_steps: int
    prefills: int
    decode_compiles: int
    prefill_compiles: int
    prefill_s: float
    decode_s: float
    outputs: tuple[tuple[int, tuple[int, ...]], ...]  # (rid, tokens) by rid
    quarantined: tuple[int, ...] = ()  # rids dropped THIS drain (budget spent)
    slot_steps: int = 0   # active-slot decode/verify invocations
    drafted: int = 0      # speculative draft tokens scored
    accepted: int = 0     # draft tokens accepted into outputs
    # decode-side dispatch accounting (ISSUE 15): compiled decode
    # invocations and the host syncs pulling their sampled tokens —
    # the two per-token costs macro-step decode amortizes to one per
    # ``macro_steps`` tokens.  For a single decoding stream,
    # dispatches == ceil(slot_steps / macro_steps) (asserted live in
    # ex24/ex32); both are registered lower-is-better in obs.regress.
    dispatches: int = 0
    host_syncs: int = 0
    # prefix-sharing accounting (the static half of the sharing claim):
    # every prompt token is either COMPUTED through a prefill program
    # (prefill_tokens) or SERVED from a shared page (shared_tokens), so
    # prefill_tokens + shared_tokens == sum of admitted prompt lengths
    # and both legs drop deterministically with the share ratio
    prefill_tokens: int = 0
    shared_tokens: int = 0
    cow_pages: int = 0          # copy-on-write page copies this drain
    fresh_kv_bytes: float = 0.0  # K/V bytes freshly written this drain
    # sub-page sharing (ISSUE 14): tokens served from a COPIED boundary
    # page past the last full-page match — included in shared_tokens,
    # broken out so the no-longer-page-quantized claim is checkable
    subpage_tokens: int = 0
    # per-request time-to-first-token, seconds from submit to the first
    # sampled token, for requests COMPLETED this drain — the router's
    # per-class p50/p99 TTFT input (fleet SLO reporting, ISSUE 14)
    ttft_s: tuple[tuple[int, float], ...] = ()
    # tiered-KV accounting (zero with kv_host_pages=0): page-granular
    # host↔device traffic — STATIC counts (exact page moves x the
    # pool's exact per-page bytes, obs.ledger.kv_host_traffic_bytes),
    # and the cold hits the prefetch-ahead failed to hide
    spilled_pages: int = 0      # payload D2H copies this drain
    prefetched_pages: int = 0   # payload H2D copies this drain
    cold_hits: int = 0          # synchronously-fetched pages
    host_bytes: float = 0.0     # spill + prefetch payload bytes

    @property
    def accept_len_mean(self) -> Optional[float]:
        """Mean accepted draft length per verify sweep (None: no sweeps)."""
        if self.slot_steps == 0:
            return None
        return self.accepted / self.slot_steps

    @property
    def shared_frac(self) -> float:
        """Fraction of admitted prompt tokens served from shared pages."""
        total = self.prefill_tokens + self.shared_tokens
        return self.shared_tokens / total if total else 0.0


@dataclasses.dataclass
class _Slot:
    rid: int
    prompt: tuple[int, ...]   # kept for deterministic replay on recovery
    pages: list[int]          # LOCAL page ids in this slot's group
    n_cached: int             # tokens whose K/V are in the cache
    max_new: int
    last_token: int
    generated: list[int]
    # prompt tokens NOT yet prefilled (context-prefill admissions only):
    # a slot with pending tokens is PREFILLING — it advances one chunk
    # per tick and joins the decode bank when the tail drains
    pending: tuple[int, ...] = ()
    # per-request stop tokens (device-side EOS, ISSUE 19): the stop
    # token itself IS emitted (it closes the output), then the slot
    # finishes; () keeps the budget-only contract byte-for-byte
    stop: tuple[int, ...] = ()


#: profiling spans kept on the engine's Timeline — a recent window, not
#: engine-lifetime history (a continuously-serving engine would otherwise
#: grow one Span per tick without bound)
_MAX_SPANS = 1024

#: the host-tier failure contract (chaos site ``serve/spill``): absorb
#: transient extent-allocation faults fast, then DEGRADE the group to
#: no-spill — only :class:`~tpuscratch.serve.kvcache.HostTierError` is
#: retryable; a compiled-call failure must take the recovery path, not
#: a retry loop
DEFAULT_SPILL_RETRY = RetryPolicy(max_attempts=3, base_s=0.005, max_s=0.05,
                                  retryable=(HostTierError,))


def macro_clamp(scfg: ServeConfig) -> tuple[int, Optional[str]]:
    """(effective macro_steps, clamping field or None) — THE macro
    width rule, one definition, shared by the engine's construction
    report and the bench's budget/page arithmetic.  Since the
    host-free lift (ISSUE 19) NOTHING clamps: speculative drafting and
    Leviathan accept/resample run inside the scan carry
    (``serve.decode.build_spec_decode_loop``) and tiered wave
    staging/prefetch overlap the running scan, so ``spec_k > 0`` and
    ``kv_host_pages > 0`` compose with ``macro_steps > 1`` instead of
    forcing per-token dispatch.  The tuple shape survives so every
    ledger/bench call site keeps one rule; the reason leg is always
    None — a stale ``"spec_k"`` / ``"kv_host_pages"`` reason must
    never reappear (test-gated)."""
    return scfg.macro_steps, None


def validate_request(req: Request, scfg: ServeConfig) -> None:
    """The admission-independent request rules — ONE definition,
    enforced at every front door (``ServeEngine.submit``,
    ``DisaggEngine.submit``, ``FleetRouter.submit``), so a malformed
    request fails at submission, never mid-dispatch."""
    if req.max_new < 1:
        raise ValueError(f"max_new must be >= 1, got {req.max_new}")
    if req.rid < 0:
        raise ValueError(f"rid must be >= 0, got {req.rid}")
    if not req.prompt:
        raise ValueError("empty prompt")
    if len(req.prompt) + req.max_new > scfg.max_seq:
        raise ValueError(
            f"request {req.rid}: prompt {len(req.prompt)} + max_new "
            f"{req.max_new} exceeds max_seq {scfg.max_seq}"
        )
    if any(t < 0 or t >= scfg.vocab for t in req.prompt):
        raise ValueError(f"request {req.rid}: token id out of vocab")
    if any(t < 0 or t >= scfg.vocab for t in req.stop_tokens):
        raise ValueError(f"request {req.rid}: stop token id out of vocab")


def init_embed(seed: int, vocab: int, d_model: int) -> jax.Array:
    """Tied token embedding / unembedding table (V, d)."""
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.standard_normal((vocab, d_model)).astype(np.float32)
        / np.sqrt(d_model)
    )


def _host_pool():
    """The process-wide pinned host pool backing the tier's bulk
    extents (``native/hostpool.py`` — the reference's L2 host_allocator
    lineage); None degrades :class:`HostPageStore` to plain numpy
    extents (unpinned, same semantics) where the native library is
    absent."""
    try:
        from tpuscratch.native import hostpool

        if hostpool.available():
            return hostpool.default_pool()
    except Exception:
        pass
    return None


def _bucket(n: int) -> int:
    """Prompt shape bucket: next power of two, floor 8 — bounds prefill
    compiles at log2(max_seq) programs."""
    b = 8
    while b < n:
        b *= 2
    return b


class ServeEngine:
    """Sharded continuous-batching engine.  ``submit`` queues requests,
    ``step`` runs one admission + decode tick, ``run`` drains.

    Slot ``s`` belongs to dp group ``s // (n_slots / dp_size)`` — the
    contiguous chunk P(dp) sharding hands that group — and its pages come
    from that group's own :class:`PageAllocator` (ids are group-local,
    matching the dp-sharded pages axis of the cache).

    ``sink`` (an ``obs.sink.Sink``; default the no-op ``NullSink``)
    receives one ``serve/tick`` event per tick plus a ``serve/report`` +
    metrics snapshot per drain; ``self.metrics`` is the live
    ``obs.MetricsRegistry`` regardless of sink.  ``recorder`` (an
    ``obs.trace.FlightRecorder``; a fresh bounded one when absent — the
    flight recorder is always on) collects the prefill/decode spans via
    the engine's Timeline for Chrome-trace export; per-phase totals are
    emitted as cumulative ``trace/phase`` events at each drain."""

    def __init__(self, mesh: Mesh, cfg: TransformerConfig, scfg: ServeConfig,
                 params: Optional[dict] = None,
                 embed: Optional[jax.Array] = None,
                 dp: str = "dp", sp: str = "sp",
                 sink=None, chaos=None, recorder=None, tracer=None):
        check_serve_mesh(mesh, cfg, dp, sp)
        self._dp_size = mesh.shape[dp]
        if scfg.n_slots % self._dp_size:
            raise ValueError(
                f"n_slots {scfg.n_slots} not divisible by dp size "
                f"{self._dp_size}"
            )
        if scfg.max_seq > scfg.n_pages * scfg.page_size:
            raise ValueError(
                f"max_seq {scfg.max_seq} exceeds one group's pool "
                f"({scfg.n_pages} pages x {scfg.page_size})"
            )
        if scfg.kv_dtype not in _KV_DTYPES:
            raise ValueError(
                f"kv_dtype {scfg.kv_dtype!r} not in {sorted(_KV_DTYPES)}"
            )
        if scfg.fused_attention not in _FUSED_MODES:
            raise ValueError(
                f"fused_attention {scfg.fused_attention!r} not in "
                f"{sorted(_FUSED_MODES)}"
            )
        if scfg.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {scfg.spec_k}")
        if scfg.spec_ngram < 1:
            raise ValueError(
                f"spec_ngram must be >= 1, got {scfg.spec_ngram}"
            )
        if scfg.chunk_prefill < 0:
            raise ValueError(
                f"chunk_prefill must be >= 0, got {scfg.chunk_prefill}"
            )
        if scfg.kv_host_pages < 0:
            raise ValueError(
                f"kv_host_pages must be >= 0, got {scfg.kv_host_pages}"
            )
        if scfg.macro_steps < 1:
            raise ValueError(
                f"macro_steps must be >= 1, got {scfg.macro_steps}"
            )
        if (scfg.prefix_share or scfg.chunk_prefill) and scfg.retry_budget:
            raise ValueError(
                "retry_budget composes with the monolithic admission "
                "path only; context-prefill admissions (prefix_share / "
                "chunk_prefill) keep the legacy raise-through contract"
            )
        self.mesh, self.cfg, self.scfg = mesh, cfg, scfg
        self._kv_jnp_dtype = _KV_DTYPES[scfg.kv_dtype]
        self._quantized = scfg.kv_dtype != "float32"
        self._fused = _FUSED_MODES[scfg.fused_attention]
        self.geom = CacheGeometry(
            cfg.n_layers, scfg.n_pages, scfg.page_size, cfg.n_heads,
            cfg.d_head,
        )
        self.params = (
            params if params is not None else init_params(scfg.seed, cfg)
        )
        self.embed = (
            embed if embed is not None
            else init_embed(scfg.seed, scfg.vocab, cfg.d_model)
        )
        if self.embed.shape != (scfg.vocab, cfg.d_model):
            raise ValueError(
                f"embed {self.embed.shape} != ({scfg.vocab}, {cfg.d_model})"
            )
        self._embed_np = np.asarray(self.embed)
        # the fresh pool COMMITS to its canonical sharding up front:
        # an uncommitted zeros pytree carries SingleDeviceSharding, so
        # the first admission would compile each prefill program against
        # THAT and the second against the program-output NamedSharding —
        # a hidden per-bucket XLA recompile (~100s of ms) on the second
        # admission that CompileCounter cannot see (the jaxpr is cached;
        # only the sharding key changed).  Committing makes every
        # invocation see one sharding, so each program compiles once.
        from tpuscratch.serve.kvcache import kv_cache_spec

        self._kv_sharding = {
            name: NamedSharding(mesh, spec)
            for name, spec in kv_cache_spec(dp, sp, self._quantized).items()
        }
        self._kv = self._fresh_kv()
        # tiered KV memory (off by default): kv_host_pages > 0 swaps the
        # per-group PageAllocator for a TieredPageAllocator over a
        # HostPageStore — the engine-facing page currency becomes a
        # LOGICAL id whose backing migrates, and every compiled-program
        # table row resolves through the allocator at build time
        self._tiered = scfg.kv_host_pages > 0
        self._cold_hits = 0
        self._allocators = self._fresh_allocators()
        self._slots: list[Optional[_Slot]] = [None] * scfg.n_slots
        self._slots_per_group = scfg.n_slots // self._dp_size
        self._queue: collections.deque[Request] = collections.deque()
        self._seen_rids: set[int] = set()
        self._chaos = chaos  # ft.ChaosPlan or None: "serve/prefill" site
        self._quarantined: dict[int, str] = {}  # rid -> last error
        # rid whose ADMISSION raised through the last tick (the
        # retry_budget == 0 raise-through contract) — _recover_cache
        # requeues every in-flight request ahead of it, so the queue
        # head does NOT name the poison; this does.  Cleared each tick;
        # the fleet router reads it to quarantine the right request.
        self._poison_rid: Optional[int] = None
        # finishes collected by an in-progress tick (see _tick_inner)
        self._finish_buf: list[tuple[int, tuple[int, ...]]] = []
        self._seed_key = jax.random.key(scfg.seed)
        self.recorder = (
            recorder if recorder is not None else FlightRecorder()
        )
        self.timeline = Timeline(self.recorder)
        # observability: every tick updates the registry (host-side
        # attribute writes, < 2% of a compiled step) and, when a sink is
        # attached, emits one JSONL event — queue depth, free-page
        # watermark, tick latency, insert/evict counts, compile counts
        self.metrics = MetricsRegistry()
        self.sink = sink if sink is not None else NullSink()
        # per-request causal tracing (obs.reqtrace): the NullReqTracer
        # path is a no-op method call per hook, so the engine holds one
        # unconditionally — the NullSink idiom
        self.tracer = tracer if tracer is not None else NullReqTracer()
        bind_sink(chaos, self.sink)  # injected ft/fault events join the stream
        bind_tracer(chaos, self.tracer)  # rid-keyed faults mark span trees
        self._tick = 0
        # effective macro-step width (macro_clamp — the one shared
        # rule): nothing clamps since the host-free lift (ISSUE 19);
        # the gauge + engine event + macro_steps_effective stay
        # ledger-visible so a regression back to per-token dispatch
        # would be caught by the existing assertions
        self._macro_T, self._macro_clamp = macro_clamp(scfg)
        self.metrics.gauge("serve/macro_steps").set(self._macro_T)
        self.sink.emit(
            "serve/engine",
            n_slots=scfg.n_slots, n_pages=scfg.n_pages,
            page_size=scfg.page_size, max_seq=scfg.max_seq,
            dp_size=self._dp_size, n_layers=cfg.n_layers,
            n_heads=cfg.n_heads, d_model=cfg.d_model,
            kv_dtype=scfg.kv_dtype, spec_k=scfg.spec_k,
            macro_steps=scfg.macro_steps,
            macro_steps_effective=self._macro_T,
            **({"macro_clamped_by": self._macro_clamp}
               if self._macro_clamp else {}),
        )
        self.decode_counter = CompileCounter()
        self.prefill_counter = CompileCounter()
        # speculation swaps the one-token decode program for ONE fixed
        # (spec_k + 1)-token verify program — still a single compile,
        # still counted by decode_counter; macro_steps > 1 swaps it for
        # ONE fixed T-token scan program, same discipline.  Composed
        # spec × macro (ISSUE 19) is a third program: one T-round scan
        # whose carry drafts, verifies, and accept/resamples on device
        # (up to T·(spec_k+1) token rounds per dispatch).
        self._decode_loop = None
        self._spec_loop = None
        if self._macro_T > 1 and scfg.spec_k > 0:
            self._decode = None
            self._spec_loop = build_spec_decode_loop(
                mesh, cfg, self.geom, self._macro_T, scfg.spec_k,
                temperature=scfg.temperature, top_k=scfg.top_k,
                ngram=scfg.spec_ngram, dp=dp, sp=sp,
                counter=self.decode_counter, quantized=self._quantized,
                fused=self._fused,
            )
        elif scfg.spec_k > 0:
            self._decode = build_verify_step(
                mesh, cfg, self.geom, scfg.spec_k, dp=dp, sp=sp,
                counter=self.decode_counter, quantized=self._quantized,
                fused=self._fused,
            )
        elif self._macro_T > 1:
            self._decode = None
            self._decode_loop = build_decode_loop(
                mesh, cfg, self.geom, self._macro_T,
                temperature=scfg.temperature, top_k=scfg.top_k,
                dp=dp, sp=sp, counter=self.decode_counter,
                quantized=self._quantized, fused=self._fused,
            )
        else:
            self._decode = build_decode_step(
                mesh, cfg, self.geom, dp=dp, sp=sp,
                counter=self.decode_counter, quantized=self._quantized,
                fused=self._fused,
            )
        self._prefills: dict[int, object] = {}  # bucket len -> program
        self._dp, self._sp = dp, sp
        # context-prefill layers (both OFF by default: self._ctx stays
        # None and the admission path is byte-for-byte the legacy one)
        self._ctx_mode = scfg.prefix_share or scfg.chunk_prefill > 0
        self._chunk = (
            scfg.chunk_prefill if scfg.chunk_prefill > 0 else scfg.page_size
        )
        self._ctx = (
            build_context_prefill(
                mesh, cfg, self.geom, self._chunk, dp=dp, sp=sp,
                counter=self.prefill_counter, quantized=self._quantized,
                fused=self._fused,
            )
            if self._ctx_mode else None
        )
        self._tries: Optional[list[PrefixCache]] = (
            [PrefixCache(scfg.page_size) for _ in range(self._dp_size)]
            if scfg.prefix_share else None
        )
        self._unembed = jax.jit(lambda o, e: o @ e.T)
        # the macro loop takes the seed key as raw key DATA (typed PRNG
        # keys don't ride shard_map argument specs); wrap_key_data
        # inside the program reproduces the fold_in chain bit-for-bit
        self._seed_key_data = jax.random.key_data(self._seed_key)
        self._decode_steps = 0
        self._prefill_count = 0
        self._tokens_generated = 0
        self._slot_steps = 0
        # decode-side dispatch accounting (ISSUE 15): compiled decode
        # program invocations, host syncs pulling their sampled tokens,
        # and token ROUNDS the bank has run (a macro tick advances
        # several rounds per dispatch; the bench's swept-byte roofline
        # scales by the round delta, not the dispatch count)
        self._dispatches = 0
        self._host_syncs = 0
        self._decode_rounds = 0
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._prefill_s = 0.0
        self._decode_s = 0.0
        self._prefill_tokens = 0
        self._shared_tokens = 0
        self._fresh_tokens = 0   # tokens whose K/V this engine wrote
        self._cow_pages = 0
        self._subpage_tokens = 0
        # TTFT bookkeeping (ISSUE 14): submit stamps arrival, the first
        # sampled token stamps delivery; the router drains via take_ttft
        self._submit_t: dict[int, float] = {}
        self._ttft: dict[int, float] = {}

    # ---- introspection (tests + report) --------------------------------

    @property
    def decode_compiles(self) -> int:
        return self.decode_counter.count

    @property
    def prefill_compiles(self) -> int:
        return self.prefill_counter.count

    def free_pages(self) -> list[int]:
        """Per-group free-page counts (the leak check reads this)."""
        return [a.n_free for a in self._allocators]

    @property
    def kv_cache_bytes(self) -> int:
        """Total cache-pool bytes (pages + quantization scales) — the
        static quantity the int8 lever shrinks; ``obs.ledger`` does the
        accounting so bench rows and regression tests share it."""
        from tpuscratch.obs.ledger import kv_cache_bytes

        return kv_cache_bytes(self._kv)

    @property
    def kv_bytes_per_token(self) -> float:
        """Cache bytes per token of pool capacity (pages + scales over
        ``dp_size * n_pages * page_size`` token slots)."""
        return self.kv_cache_bytes / (self._dp_size * self.geom.max_tokens)

    @property
    def cached_pages(self) -> int:
        """Pages the NEXT decode sweep will gather: sum over live slots
        of ceil(cached length / page_size).  The bench's roofline
        accounting multiplies this by the pool's exact per-token bytes
        (``kv_bytes_per_token`` — payload + amortized scale planes) to
        get the HBM bytes one tick's sweep moves, the denominator-free
        half of the achieved-fraction-of-peak measurement
        (``bench.decode_bench``)."""
        page = self.scfg.page_size
        return sum(
            -(-s.n_cached // page) for s in self._slots if s is not None
        )

    @property
    def tokens_generated(self) -> int:
        """Engine-lifetime emitted tokens (benches read deltas)."""
        return self._tokens_generated

    @property
    def macro_steps_effective(self) -> int:
        """Tokens per decode dispatch after clamping (see
        ``ServeConfig.macro_steps``); 1 means the per-token program."""
        return self._macro_T

    @property
    def macro_clamped_by(self) -> Optional[str]:
        """The config field that clamped ``macro_steps`` to 1 — always
        None since the host-free lift (ISSUE 19; ``spec_k`` and
        ``kv_host_pages`` compose with macro scans now).  Kept as the
        ledger-visible half of the old contract so a stale reason
        reappearing is test-detectable."""
        return self._macro_clamp

    @property
    def dispatches(self) -> int:
        """Engine-lifetime compiled DECODE-side dispatches (plain
        sweeps, speculative sweeps, macro scans — not prefill).  Under
        macro decode one dispatch covers up to ``macro_steps`` token
        rounds: ``dispatches == ceil(slot_steps / macro_steps)`` for a
        single decoding stream (asserted live in ex24/ex32)."""
        return self._dispatches

    @property
    def host_syncs(self) -> int:
        """Engine-lifetime decode-side host synchronizations (sampled
        tokens pulled to the host — the per-token blocking transfer
        macro decode amortizes to one per T tokens)."""
        return self._host_syncs

    @property
    def decode_rounds(self) -> int:
        """Engine-lifetime decode token ROUNDS: iterations in which
        every active slot swept its cached pages once.  One per
        decode/spec tick; up to ``macro_steps`` per macro dispatch.
        The bench's static swept-byte accounting multiplies sampled
        page counts by the per-tick round delta — without it a macro
        tick's sweep bytes would be under-counted ~T×."""
        return self._decode_rounds

    @property
    def slot_steps(self) -> int:
        """Engine-lifetime active-slot decode/verify invocations."""
        return self._slot_steps

    @property
    def spec_drafted(self) -> int:
        return self._spec_drafted

    @property
    def spec_accepted(self) -> int:
        return self._spec_accepted

    @property
    def prefill_tokens(self) -> int:
        """Engine-lifetime prompt tokens COMPUTED through a prefill
        program (monolithic or context-chunk) — the prefill-FLOP leg
        prefix sharing shrinks."""
        return self._prefill_tokens

    @property
    def shared_tokens(self) -> int:
        """Engine-lifetime prompt tokens served from shared pages."""
        return self._shared_tokens

    @property
    def cow_pages(self) -> int:
        """Engine-lifetime copy-on-write page copies."""
        return self._cow_pages

    @property
    def subpage_tokens(self) -> int:
        """Engine-lifetime tokens served from COPIED boundary pages
        past the last full-page match (sub-page sharing, ISSUE 14) —
        a subset of ``shared_tokens``."""
        return self._subpage_tokens

    def _mark_first_token(self, rid: int) -> None:
        """Stamp TTFT at the FIRST sampled token (idempotent: a replay
        after recovery, or the decode-side re-admission of a staged
        request, keeps the original stamp)."""
        t0 = self._submit_t.pop(rid, None)
        if rid not in self._ttft:
            now = time.perf_counter()
            self._ttft[rid] = now - t0 if t0 is not None else 0.0
            self.tracer.mark(rid, "first_token", now)
            if len(self._ttft) > 4096:
                # bounded for step()-driven serving loops that never
                # read TTFT (run() pops at report, the router pops per
                # finish): oldest stamps age out, never accumulate
                self._ttft.pop(next(iter(self._ttft)))

    def take_ttft(self, rid: int) -> Optional[float]:
        """Pop one finished request's time-to-first-token (seconds from
        submit to first sampled token); None when never stamped.  The
        fleet router reads per-request TTFT here as requests finish —
        rids it consumed no longer appear in ``GenerateReport.ttft_s``."""
        return self._ttft.pop(rid, None)

    def prefix_match_tokens(self, prompt: Sequence[int]) -> int:
        """Longest prefix (in TOKENS) of ``prompt`` this engine's
        prefix index can serve from registered pages: the full-page
        trie chain plus the sub-page boundary continuation.  Zero
        without ``prefix_share`` — the router's fleet-level affinity
        index reads this per replica (ISSUE 14)."""
        if self._tries is None:
            return 0
        best = 0
        for g, trie in enumerate(self._tries):
            alloc = self._allocators[g]
            m = len(trie.match(prompt))
            _, n_sub = trie.match_tail(
                prompt, m, prefer=lambda p: alloc.refcount(p) > 0
            )
            best = max(best, m * self.scfg.page_size + n_sub)
        return best

    @property
    def fresh_kv_bytes(self) -> float:
        """Engine-lifetime K/V bytes freshly written into the pool
        (prefilled prompt tokens + generated tokens, at this pool's
        exact per-token byte cost incl. quantization scales) — shared
        admissions write none for their shared prefix, so this drops
        with the share ratio.  Static accounting, not sampled: token
        counts are exact and the per-token bytes come from the pool
        geometry (``obs.ledger.kv_cache_bytes`` over capacity)."""
        return self._fresh_tokens * self.kv_bytes_per_token

    @property
    def n_active(self) -> int:
        return sum(s is not None for s in self._slots)

    @property
    def n_queued(self) -> int:
        return len(self._queue)

    def validate(self, req: Request) -> None:
        """Would :meth:`submit` accept ``req``?  Raises the engine's
        rejection otherwise — the stateless half of admission (rid
        reuse stays submit's job), so a front end (the fleet router)
        can enforce EVERY replica's rules at its own door instead of
        raising out of a later dispatch."""
        validate_request(req, self.scfg)
        self.validate_local(req)

    def validate_local(self, req: Request) -> None:
        """The replica-SPECIFIC half of :meth:`validate`: rules that
        can differ between output-compatible replicas (none here; the
        disagg front end adds its staging-pool bound).  A fleet front
        end runs the common ``validate_request`` once and this per
        replica — N prompts scans would otherwise be N-for-1 work."""

    def stamp_submit(self, rid: int, t0: Optional[float] = None) -> None:
        """Start ``rid``'s TTFT clock without queueing — the disagg
        front end stamps arrival here before staging; ``t0`` back-dates
        to an earlier arrival (idempotent: the first stamp wins)."""
        self._submit_t.setdefault(
            rid, time.perf_counter() if t0 is None else t0
        )

    def take_poison_rid(self) -> Optional[int]:
        """Pop the rid whose admission raised through the last tick
        (None when the raise was not attributable to one request) —
        the fleet router's quarantine handle."""
        rid, self._poison_rid = self._poison_rid, None
        return rid

    def drop_queued(self, rid: int) -> bool:
        """Remove ``rid`` from the request queue (True when found) —
        how a front end retracts a request the engine requeued under
        the raise-through contract."""
        for req in list(self._queue):
            if req.rid == rid:
                self._queue.remove(req)
                return True
        return False

    @property
    def has_buffered_finishes(self) -> bool:
        """True when a raise-through tick parked finishes that the
        next tick will emit (see ``_tick_inner``)."""
        return bool(self._finish_buf)

    def is_quarantined(self, rid: int) -> bool:
        """Membership check without the ``quarantined`` property's
        dict copy — the router probes every in-flight rid per tick."""
        return rid in self._quarantined

    def quarantine(self, rid: int, reason: str, attempts: int = 1) -> None:
        """Mark ``rid`` quarantined — reported, never requeued — the
        ONE owner of the bookkeeping (quarantine map, TTFT stamp drop,
        counter, sink event), shared by the in-engine retry path and
        the fleet router's raise-through handling."""
        self._quarantined[rid] = reason
        self._submit_t.pop(rid, None)
        self.metrics.counter("serve/quarantined").inc()
        self.tracer.finish(rid, time.perf_counter(),
                           outcome="quarantined")
        self.sink.emit("ft/quarantine", rid=rid, attempts=attempts,
                       error=reason)

    @property
    def quarantined(self) -> dict[int, str]:
        """{rid: last error} of requests dropped after the retry budget."""
        return dict(self._quarantined)

    def evacuate(self) -> list[tuple[int, int, int]]:
        """Kill this replica (fleet-scale chaos, ISSUE 17): tear down
        every piece of SERVING state — slots, queue, buffered finishes,
        cache pool, prefix tries, parked chains, the rid registry and
        TTFT stamps — and return what the dead process still owed, one
        ``(rid, unaccounted_prompt_tokens, lost_generated_tokens)``
        triple per unfinished request:

        - ``unaccounted``: the prompt suffix this engine never ran a
          prefill/share program over (a queued request: its whole
          prompt; a chunked slot mid-prefill: its pending tail; an
          admitted slot or a buffered finish: 0).  The fleet router
          re-admits victims from its OWN pending records, and the
          counter law stays EXACT under churn: every token the dead
          engine did account for (``len(prompt) - unaccounted``) is a
          re-admitted leg the final drain computes again.
        - ``lost_generated``: tokens this engine sampled and then
          threw away with the pool — the decode-side waste the
          goodput fraction charges to the kill.

        The engine OBJECT survives as the re-join replica: compiled
        programs are process state our simulation keeps (re-join cost
        is modeled by the router's down window, not by recompiling),
        but its scheduling state starts empty — ``_seen_rids`` clears
        with it, since the fleet-level ``FleetRouter._seen`` set is
        what guards rid uniqueness across the kill.  Lifetime counters
        (prefill/shared/subpage, dispatches) are OUR accounting, not
        the process's, and keep accumulating across the kill.

        rids key the PRNG streams, so the re-admitted victims replay
        bit-identically wherever they land — the ``_recover_cache``
        determinism contract at fleet scope."""
        owed: list[tuple[int, int, int]] = []
        for s, st in enumerate(self._slots):
            if st is None:
                continue
            owed.append((st.rid, len(st.pending), len(st.generated)))
            if self._tiered:
                # no parking: the trie is about to clear, and a parked
                # copy of a page from a dead pool must not survive it
                self._allocators[self._group_of(s)].free(st.pages)
            else:
                self._free_slot_pages(s, st)
            self._slots[s] = None
        for req in self._queue:
            owed.append((req.rid, len(req.prompt), 0))
        for rid, toks in self._finish_buf:
            # complete but undelivered: the finish died with the
            # process — fully accounted prompt, fully lost output
            owed.append((rid, 0, len(toks)))
        self._queue.clear()
        self._finish_buf = []
        if self._tries is not None:
            for trie in self._tries:
                trie.clear()
        if self._tiered:
            for a in self._allocators:
                a.drop_parked()
        self._kv = self._fresh_kv()
        self._seen_rids.clear()
        self._submit_t.clear()
        self._ttft.clear()
        self._poison_rid = None
        self.metrics.counter("serve/evacuated").inc(len(owed))
        if self.tracer.enabled and owed:
            # the kill edge of every victim's trace: the current
            # attempt's spans become waste, the re-admission wait opens
            now = time.perf_counter()
            for rid, _unaccounted, lost in owed:
                self.tracer.killed(rid, now, lost_tokens=lost)
        self.sink.emit("serve/evacuate", owed=len(owed))
        return owed

    def _group_of(self, slot: int) -> int:
        return slot // self._slots_per_group

    def _last_span_s(self) -> float:
        """Seconds of the span just recorded; trims the Timeline to a
        recent window so a long-lived engine's span list stays bounded."""
        s = self.timeline.spans[-1].seconds
        if len(self.timeline.spans) > _MAX_SPANS:
            del self.timeline.spans[: -_MAX_SPANS]
        return s

    def set_tracer(self, tracer) -> None:
        """Attach a per-request tracer (``obs.reqtrace.ReqTracer``) —
        the fleet router propagates ONE shared tracer to every replica
        so a request's tree stays whole across dispatch and
        re-admission."""
        self.tracer = tracer if tracer is not None else NullReqTracer()
        bind_tracer(self._chaos, self.tracer)

    def _trace_span(self, rids: Sequence[int], kind: str, **args) -> None:
        """Fan the timeline span just closed out to ``rids`` as one
        work span each — the tracer reuses the Timeline's perf_counter
        stamps, so tracing adds NO clock reads to the hot path."""
        sp = self.timeline.spans[-1]
        self.tracer.work_batch(rids, kind, sp.begin, sp.end, **args)

    def _fresh_kv(self) -> dict:
        """A zeroed pool committed to the canonical cache sharding."""
        return {
            name: jax.device_put(leaf, self._kv_sharding[name])
            for name, leaf in init_kv_cache(
                self.geom, self._dp_size, self._kv_jnp_dtype
            ).items()
        }

    # ---- the host paging tier (ISSUE 13) -------------------------------

    def _fresh_allocators(self) -> list:
        """One allocator per dp group: plain :class:`PageAllocator`
        untiered, :class:`TieredPageAllocator` over a fresh
        :class:`HostPageStore` when ``kv_host_pages > 0``."""
        if not self._tiered:
            return [PageAllocator(self.scfg.n_pages)
                    for _ in range(self._dp_size)]
        return [self._tier_allocator(g) for g in range(self._dp_size)]

    def _tier_allocator(self, group: int) -> TieredPageAllocator:
        store = HostPageStore(
            self.scfg.kv_host_pages,
            host_leaf_shapes(self.geom, self._kv_jnp_dtype),
            pool=_host_pool(),
            alloc_hook=self._spill_hook,
        )
        return TieredPageAllocator(
            self.scfg.n_pages, store,
            reader=self._tier_reader(group),
            writer=self._tier_writer(group),
            policy=ResidencyPolicy(),
            on_parked_evict=lambda lps, g=group: self._drop_parked(g, lps),
        )

    def _spill_hook(self, nbytes: int) -> None:
        """Fires before every host-tier extent allocation — the
        ``serve/spill`` chaos site (an injected fault surfaces as
        :class:`HostTierError` through the store, retried then degraded
        by :meth:`_tier_op`)."""
        if self._chaos is not None:
            self._chaos.maybe_fail("serve/spill", op="serve/spill")

    def _drop_parked(self, group: int, lps: list) -> None:
        """A parked chain page was LRU-evicted from the host tier:
        forget its trie mappings (it can no longer be restored)."""
        if self._tries is not None:
            self._tries[group].drop(lps)

    def _tier_reader(self, group: int):
        """The D2H spill leg: batch-read device pages off the live
        cache pytree as host numpy (batch axis 0, exact bytes)."""
        off = group * self.geom.n_pages

        def reader(dids: list) -> dict:
            idx = np.asarray([off + d for d in dids])
            return {
                name: np.moveaxis(np.asarray(buf[:, idx]), 1, 0)
                for name, buf in self._kv.items()
            }

        return reader

    def _tier_writer(self, group: int):
        """The H2D prefetch leg: batch-land host page records back into
        the live pool (ONE functional scatter per leaf, dispatched
        async — the compiled sweep behind it proceeds while the copy
        flies, which is what double-buffering means here)."""
        off = group * self.geom.n_pages

        def writer(dids: list, payloads: dict) -> None:
            idx = jnp.asarray([off + d for d in dids])
            for name in self._kv:
                batch = jnp.moveaxis(jnp.asarray(payloads[name]), 0, 1)
                self._kv[name] = self._kv[name].at[:, idx].set(batch)

        return writer

    def _tier_op(self, group: int, fn):
        """Run a host-tier-touching allocator operation under the spill
        failure contract: transient :class:`HostTierError`s (chaos site
        ``serve/spill``, real extent-allocation failures) retry through
        ``ft.retry``; exhaustion DEGRADES the group to no-spill —
        device-only admission arithmetic, byte-identical output, fewer
        residents — and re-runs the operation once device-only."""
        alloc = self._allocators[group]
        if not self._tiered or alloc.degraded:
            return fn()
        try:
            return ft_retry(fn, DEFAULT_SPILL_RETRY, op="serve/spill")
        except HostTierError as exc:
            alloc.degrade()
            self.metrics.counter("serve/spill_degraded").inc()
            self.sink.emit(
                "ft/degrade", site="serve/spill", group=group,
                error=f"{type(exc).__name__}: {exc}",
            )
            return fn()

    def _update_pins(self) -> None:
        """Re-pin the hot window: each live slot's tail pages (its write
        frontier, touched by every sweep it joins) are never spill
        victims — the residency policy's pinned half."""
        if not self._tiered:
            return
        pins: list[set] = [set() for _ in range(self._dp_size)]
        tail = max(1, self._allocators[0].policy.pin_tail)
        page = self.geom.page_size
        for s, st in enumerate(self._slots):
            if st is None:
                continue
            n_pg = min(len(st.pages), -(-max(1, st.n_cached + 1) // page))
            pins[self._group_of(s)].update(st.pages[max(0, n_pg - tail):n_pg])
        for g, a in enumerate(self._allocators):
            a.set_pins(pins[g])

    def _frontier(self, st: _Slot, k_new: int) -> list:
        """The logical pages one sweep of this slot touches: everything
        holding positions [0, n_cached + k_new) — the attention gather
        plus the write frontier.  Reserved pages past it stay in
        whatever tier they are (their table entries are the sentinel:
        masked, never read — untiered garbage-page semantics)."""
        n_pg = min(len(st.pages),
                   -(-(st.n_cached + k_new) // self.geom.page_size))
        return st.pages[:n_pg]

    def _sweep_row(self, group: int, st: _Slot, k_new: int) -> list:
        """The page-table row a sweep gets: the slot's full (logical ==
        physical) list untiered; the frontier resolved to DEVICE ids
        when tiered (``ensure_resident`` ran first)."""
        if not self._tiered:
            return st.pages
        alloc = self._allocators[group]
        return [alloc.device_page(lp) for lp in self._frontier(st, k_new)]

    def _page_dev(self, group: int, lp: int) -> int:
        """One write-target page id for the compiled program."""
        if not self._tiered:
            return lp
        return self._allocators[group].device_page(lp)

    def _plan_waves(self, slots: list, k_of) -> list[list]:
        """Wave-partition this tick's sweeping slots (see
        ``serve.decode.plan_sweep_waves``); one wave — the whole bank —
        untiered or when everything fits.

        With prefix sharing on, a slot whose write-target pages are
        SHARED will copy-on-write inside the sweep — one fresh device
        page per shared target, while the original stays held by its
        other sharers — so each such page adds a synthetic element to
        the slot's footprint: a wave packed to exactly the pool size
        could otherwise not seat its own CoW expansion."""
        if not self._tiered:
            return [list(slots)]
        page = self.geom.page_size
        needs = []
        for s in slots:
            st = self._slots[s]
            front = set(self._frontier(st, k_of(s)))
            if self._tries is not None:
                alloc = self._allocators[self._group_of(s)]
                first = st.n_cached // page
                last = (st.n_cached + max(1, k_of(s)) - 1) // page
                for i, lp in enumerate(st.pages[first:last + 1]):
                    if alloc.refcount(lp) > 1:
                        front.add(("cow", s, first + i))
            needs.append((s, self._group_of(s), frozenset(front)))
        waves = plan_sweep_waves(needs, self.scfg.n_pages)
        self.metrics.counter("serve/sweep_waves").inc(len(waves))
        if len(waves) > 1:
            # ledger the waves the affinity reorder saved over legacy
            # slot-order first-fit (ISSUE 14); a single wave can never
            # be beaten, so the baseline plan is skipped there
            base = plan_sweep_waves(needs, self.scfg.n_pages,
                                    reorder=False)
            if len(base) > len(waves):
                self.metrics.counter("serve/waves_saved").inc(
                    len(base) - len(waves)
                )
        return waves

    def _stage_wave(self, slots: list, k_of, best_effort: bool = False,
                    hold: tuple = ()) -> int:
        """Make one wave's frontier pages device-resident.  The
        synchronous form (``best_effort=False``) is the COLD-HIT
        fallback — pages the prefetch-ahead failed to land block here,
        counted and timed (``serve/cold_hit_s``); the best-effort form
        is the prefetch-ahead itself, fetching what fits behind the
        running sweep and leaving the rest cold.  ``hold`` shields the
        currently-sweeping wave's pages from being chosen as victims."""
        if not self._tiered or not slots:
            return 0
        by_group: dict[int, list] = {}
        for s in slots:
            st = self._slots[s]
            by_group.setdefault(self._group_of(s), []).extend(
                self._frontier(st, k_of(s))
            )
        hold_by_group: dict[int, list] = {}
        for s in hold:
            st = self._slots[s]
            if st is not None:
                hold_by_group.setdefault(self._group_of(s), []).extend(
                    self._frontier(st, k_of(s))
                )
        cold = 0
        t0 = time.perf_counter()
        for g, lps in by_group.items():
            alloc = self._allocators[g]
            keep = hold_by_group.get(g, ())

            def op(a=alloc, pages=lps, k=keep):
                return a.ensure_resident(pages, keep=k,
                                         best_effort=best_effort)

            try:
                cold += self._tier_op(g, op)
            except HostTierError:
                # even degraded the tier cannot seat this wave (live
                # pages exceed the device pool mid-outage): recover —
                # every in-flight request replays deterministically
                self._recover_cache()
                raise
            alloc.touch(lps)
        if cold and not best_effort:
            self._cold_hits += cold
            self.metrics.counter("serve/cold_hits").inc(cold)
            self.metrics.histogram("serve/cold_hit_s").observe(
                time.perf_counter() - t0
            )
        return cold

    @property
    def kv_page_bytes(self) -> float:
        """Exact bytes ONE page moves across the tiers (payload + scale
        rows) — ``obs.ledger.kv_page_bytes`` over the live pool."""
        from tpuscratch.obs.ledger import kv_page_bytes

        return kv_page_bytes(self._kv)

    @property
    def host_spilled_pages(self) -> int:
        """Engine-lifetime payload D2H page copies."""
        if not self._tiered:
            return 0
        return sum(a.spilled_pages for a in self._allocators)

    @property
    def host_prefetched_pages(self) -> int:
        """Engine-lifetime payload H2D page copies (incl. parked-chain
        restores)."""
        if not self._tiered:
            return 0
        return sum(a.prefetched_pages for a in self._allocators)

    @property
    def cold_hits(self) -> int:
        """Engine-lifetime synchronously-fetched (not prefetched-ahead)
        pages — the cold-path counter whose p99 the bench states."""
        return self._cold_hits

    @property
    def host_traffic_bytes(self) -> float:
        """Engine-lifetime host↔device paging bytes — STATIC accounting
        (exact page-move counts x exact per-page bytes), the ledger
        proof form (``obs.ledger.kv_host_traffic_bytes``)."""
        return (
            (self.host_spilled_pages + self.host_prefetched_pages)
            * self.kv_page_bytes
        )

    def _free_slot_pages(self, slot: int, st: _Slot) -> None:
        """Drop this slot's holds; pages whose LAST holder left leave
        the prefix trie too (a dead page must never be matched) —
        EXCEPT, under the tier, trie-registered pages, which PARK in
        the host tier instead of dying: the warm-prefix pool, so a
        shared chain no longer needs a concurrently-live holder (the
        PR-8 retention remainder).  Parked chains stay matchable and a
        later hit restores them (``_share_plan``)."""
        group = self._group_of(slot)
        alloc = self._allocators[group]
        if self._tiered:
            park = ()
            if self._tries is not None:
                trie = self._tries[group]
                park = [lp for lp in st.pages if trie.registered(lp)]
            # no _tier_op wrap: free() absorbs host-tier failures
            # internally (a chain that cannot park just dies — it is
            # cache), and retrying a partially-applied free would
            # double-free
            released = alloc.free(st.pages, park=park)
        else:
            released = alloc.free(st.pages)
        if self._tries is not None and released:
            self._tries[group].drop(released)

    def _recover_cache(self) -> None:
        """A compiled call raised mid-flight: its DONATED cache buffers
        may already be consumed, so serving cannot continue on the old
        pool.  Reset it and requeue every in-flight request from its
        original prompt — rids key the PRNG streams, so the replay
        regenerates the SAME tokens and a caller that catches the error
        and drains again loses nothing.  The prefix trie clears with the
        pool: a zeroed page holds no one's prefix."""
        for s, st in enumerate(self._slots):
            if st is None:
                continue
            if self._tiered:
                # no parking: the trie is about to clear, and a parked
                # copy of a page from a dead pool must not survive it
                self._allocators[self._group_of(s)].free(st.pages)
            else:
                self._free_slot_pages(s, st)
            self._slots[s] = None
            self._queue.appendleft(
                Request(rid=st.rid, prompt=st.prompt, max_new=st.max_new,
                        stop_tokens=st.stop)
            )
            if self.tracer.enabled:
                self.tracer.mark(st.rid, "replay", time.perf_counter())
        if self._tries is not None:
            for trie in self._tries:
                trie.clear()
        if self._tiered:
            # host copies mirror a pool that no longer exists: drop the
            # parked pool (the allocators themselves survive — a grant
            # made by an in-flight external admission, e.g. a disagg
            # handoff mid-retry, stays valid and is simply rewritten)
            for a in self._allocators:
                a.drop_parked()
        self._kv = self._fresh_kv()

    # ---- request lifecycle ---------------------------------------------

    def submit(self, req: Request, t0: Optional[float] = None) -> None:
        """Queue ``req``.  ``t0`` back-dates the TTFT clock to an
        earlier arrival stamp (the fleet router passes its own submit
        time so queue-held wall never looks free)."""
        self.validate(req)
        if req.rid in self._seen_rids:
            # rids key the PRNG streams AND the report's outputs map — a
            # reuse would silently drop one output and sample identical
            # token streams for both
            raise ValueError(f"request id {req.rid} already used")
        self._seen_rids.add(req.rid)
        self.stamp_submit(req.rid, t0)
        # idempotent for rids the router already began; cls stays the
        # router's when one was set there
        self.tracer.begin(req.rid, self._submit_t[req.rid])
        self._queue.append(req)

    def admit_prefilled(self, req: Request, slot: int, pages: list[int],
                        first_token: int) -> None:
        """Install an EXTERNALLY-prefilled request directly into
        ``slot`` — the disaggregated handoff path (serve/disagg.py):
        the request's whole prompt K/V already sits in THIS engine's
        cache pool under ``pages`` (migrated in from the prefill
        slice), and ``first_token`` is the token its prefill sampled
        (stream position 0), so decode continues exactly where the
        monolithic admission would.  ``pages`` must have been allocated
        from the slot's group allocator by the caller and must cover
        the request's full footprint (prompt + budget); the slot must
        be free.  Counted as an emitted token but NOT as an engine
        prefill — this engine ran no prefill program for it."""
        if self._slots[slot] is not None:
            raise ValueError(f"slot {slot} is busy")
        if req.rid in self._seen_rids:
            raise ValueError(f"request id {req.rid} already used")
        need = self.geom.pages_for(len(req.prompt) + req.max_new)
        if len(pages) < need:
            raise ValueError(
                f"request {req.rid} needs {need} pages, got {len(pages)}"
            )
        self._seen_rids.add(req.rid)
        self._tokens_generated += 1
        self.tracer.mark(req.rid, "admit_prefilled", time.perf_counter())
        self._mark_first_token(req.rid)
        self._slots[slot] = _Slot(
            rid=req.rid, prompt=req.prompt, pages=list(pages),
            n_cached=len(req.prompt), max_new=req.max_new,
            last_token=first_token, generated=[first_token],
            stop=req.stop_tokens,
        )

    def _share_plan(self, req: Request,
                    group: int) -> tuple[list[int], bool, int, int]:
        """(shared pages, full_aligned, pages to NEWLY allocate, pages
        that must be DEVICE-resident at admission) for admitting
        ``req`` into ``group`` — the refcount-aware admission
        arithmetic the watermark gate and ``_admit_ctx`` share, so the
        gate can never promise pages the admission then over-draws.

        ``full_aligned`` marks the whole-prompt page-aligned match: the
        admission must RE-SCORE the last prompt position for its
        logits, and that write needs a private copy of the last shared
        page — so one page of the allocation is the copy-on-write
        budget (the shared page itself stays untouched for its other
        holders).

        Under the tier a matched chain may include PARKED pages (warm
        prefixes retained past their last holder): a live page attaches
        (refcount + 1, free), a parked one RESTORES — a fresh private
        device-resident page filled from the host copy — so restores
        count in the allocation need and in the resident floor, and a
        fully-aligned match ending on a parked page needs no
        copy-on-write (the restored copy is already private)."""
        n_tok = len(req.prompt)
        total = self.geom.pages_for(n_tok + req.max_new)
        if self._tries is None:
            # no sharing index: the monolithic prefill writes the whole
            # prompt in ONE program, so its pages must be device-
            # resident at admission; a chunked (ctx-mode) admission
            # writes lazily — each chunk's sweep stages its own pages
            resident = 0
            if self._tiered and not self._ctx_mode:
                resident = self.geom.pages_for(n_tok)
            return [], False, total, min(total, resident)
        alloc = self._allocators[group]
        prefer = (
            (lambda p: alloc.refcount(p) > 0) if self._tiered else None
        )
        shared = self._tries[group].match(req.prompt, prefer=prefer)
        m = len(shared)
        full_aligned = m > 0 and m * self.geom.page_size == n_tok
        if not self._tiered:
            need = total - m + (1 if full_aligned else 0)
            return shared, full_aligned, need, 0
        n_live = sum(1 for p in shared if alloc.refcount(p) > 0)
        n_restore = m - n_live
        cow = 1 if (full_aligned and alloc.refcount(shared[-1]) > 0) else 0
        need = total - n_live + cow
        # restores + the CoW target are written before any chunk runs;
        # the rest of the context-prefill footprint pages in lazily
        resident = min(need, n_restore + cow)
        return shared, full_aligned, need, resident

    def _find_slot(self, req: Request) -> Optional[int]:
        needs: dict[int, tuple] = {}  # the plan depends only on the group
        for s, slot in enumerate(self._slots):
            if slot is None:
                group = self._group_of(s)
                # refcount-aware watermark: a shared-prefix admission
                # allocates only its UNSHARED pages, so the gate counts
                # those — not the request's whole footprint (shared
                # pages are already live and consume no free capacity)
                if group not in needs:
                    plan = self._share_plan(req, group)
                    needs[group] = (plan[2], plan[3])
                need, resident = needs[group]
                alloc = self._allocators[group]
                if self._tiered:
                    # cross-tier gate: device room for the written-now
                    # part, device + host capacity for the whole grant
                    # (the same arithmetic alloc() runs — shared code)
                    if alloc.can_alloc(need, resident=resident):
                        return s
                elif alloc.n_free >= need:
                    return s
        return None

    def _sample(self, keys, logits):
        return sample_batch(
            keys, logits, self.scfg.temperature, self.scfg.top_k
        )

    def _admit(self, req: Request, slot: int,
               finished: Optional[list] = None) -> bool:
        """Prefill ``req`` into ``slot``; True when the slot was taken.

        With ``prefix_share`` or ``chunk_prefill`` set the admission
        routes through :meth:`_admit_ctx` (context-prefill path);
        otherwise this is the legacy monolithic program, byte-for-byte.

        With ``scfg.retry_budget == 0`` (default) a prefill failure keeps
        the legacy contract: grant returned, request requeued at the
        head, cache recovered, exception re-raised.  With a budget,
        failed attempts are retried in-engine (the cache reset + replay
        between attempts, so transient faults complete with outputs
        byte-identical to a fault-free run) and a request that exhausts
        ``1 + retry_budget`` attempts is QUARANTINED: its grant is
        returned, it never requeues, and the engine moves on — the
        deterministic-poison livelock the unconditional requeue had."""
        if self._ctx_mode:
            return self._admit_ctx(req, slot, finished)
        geom, scfg = self.geom, self.scfg
        group = self._group_of(slot)
        n_tok = len(req.prompt)
        total = geom.pages_for(n_tok + req.max_new)
        if self._tiered:
            # prompt pages device-resident (the prefill program writes
            # them NOW); the generation-budget tail is a host-side
            # reservation — no payload exists yet, so its "pages" cost
            # zero device room and zero bytes until the write frontier
            # arrives and the sweep staging pulls them up
            n_pp = geom.pages_for(n_tok)
            pages = self._tier_op(
                group,
                lambda: self._allocators[group].alloc(
                    total, resident=n_pp
                ),
            )
            if pages is None:
                # the gate raced a degrade/park shift: retry next tick
                self._queue.appendleft(req)
                return False
            self._allocators[group].mark_written(pages[:n_pp])
            self._allocators[group].touch(pages)
            row = [self._allocators[group].device_page(lp)
                   for lp in pages[:n_pp]]
        else:
            pages = self._allocators[group].alloc(total)
            assert pages is not None  # _find_slot checked the watermark
            row = pages
        bucket = _bucket(n_tok)
        if bucket not in self._prefills:
            self._prefills[bucket] = build_prefill(
                self.mesh, self.cfg, geom, dp=self._dp, sp=self._sp,
                counter=self.prefill_counter, quantized=self._quantized,
            )
        x = np.zeros((bucket, self.cfg.d_model), np.float32)
        x[:n_tok] = self._embed_np[list(req.prompt)]
        page_rows = np.full(
            (self._dp_size, scfg.max_pages), geom.n_pages, np.int32
        )
        page_rows[group, : len(row)] = row

        def attempt() -> int:
            if self._chaos is not None:
                self._chaos.maybe_fail("serve/prefill", key=req.rid,
                                       op="serve/prefill")
            with self.timeline.span("serve/prefill"):
                out, self._kv = self._prefills[bucket](
                    self.params, self._kv, jnp.asarray(x),
                    jnp.asarray(page_rows), jnp.int32(n_tok),
                )
                logits = self._unembed(out[n_tok - 1][None], self.embed)
                return int(
                    self._sample(
                        request_key(scfg.seed, req.rid, 0)[None], logits
                    )[0]
                )

        if scfg.retry_budget == 0:
            try:
                tok = attempt()
            except Exception:
                # a failing prefill (transient device error, first-bucket
                # compile OOM) must not bleed the pool dry across retries:
                # return the grant, put the request back at the head, and
                # reset the (possibly donated-and-consumed) cache — every
                # in-flight request requeues for deterministic replay
                self._allocators[group].free(pages)
                if self.tracer.enabled:
                    # the span context manager committed the failed
                    # bracket before re-raising: charge it as waste
                    self._trace_span((req.rid,), "prefill", failed=True)
                self._queue.appendleft(req)
                self._recover_cache()
                self._poison_rid = req.rid
                raise
        else:
            tok = None
            attempts = 1 + scfg.retry_budget
            for a in range(attempts):
                try:
                    tok = attempt()
                    break
                except Exception as exc:
                    self.metrics.counter("serve/prefill_failures").inc()
                    if self.tracer.enabled:
                        self._trace_span((req.rid,), "prefill",
                                         failed=True, attempt=a)
                    # the donated cache may be consumed: reset it and
                    # requeue every IN-FLIGHT request (rids key the PRNG
                    # streams, so their replay is byte-identical); THIS
                    # request keeps its grant for the next attempt
                    self._recover_cache()
                    if a + 1 >= attempts:
                        self._allocators[group].free(pages)
                        self.quarantine(
                            req.rid, f"{type(exc).__name__}: {exc}",
                            attempts=attempts,
                        )
                        return False
                    if self.sink.enabled:
                        self.sink.emit("ft/prefill_retry", rid=req.rid,
                                       attempt=a + 1,
                                       error=f"{type(exc).__name__}: {exc}")
        self._prefill_s += self._last_span_s()
        if self.tracer.enabled:
            self._trace_span((req.rid,), "prefill", tokens=n_tok)
        self._prefill_count += 1
        self._tokens_generated += 1
        self._mark_first_token(req.rid)
        self._prefill_tokens += n_tok
        self._fresh_tokens += n_tok
        self._slots[slot] = _Slot(
            rid=req.rid, prompt=req.prompt, pages=pages, n_cached=n_tok,
            max_new=req.max_new, last_token=tok, generated=[tok],
            stop=req.stop_tokens,
        )
        return True

    def _admit_ctx(self, req: Request, slot: int,
                   finished: Optional[list] = None) -> bool:
        """Context-prefill admission: attach to shared prefix pages (if
        ``prefix_share`` matched any), allocate only the unshared
        footprint, and queue the unshared prompt tail as the slot's
        ``pending`` chunk stream.

        - tail path: the tail (>= 1 token) prefills through the
          context program, attending the shared pages it skipped;
        - full-aligned path: EVERY prompt page was matched, so the only
          compute left is re-scoring the last prompt position for its
          logits — and since that write lands in the last shared page,
          the page is copy-on-written into this admission's reserved
          budget first (the other holders' view is untouched).

        With ``chunk_prefill == 0`` (prefix sharing alone) the whole
        tail drains inside this call — monolithic admission latency
        semantics, chunked numerics; with a chunk budget the tail
        advances one chunk per engine tick instead (``_ctx_step``).

        Failures keep the legacy contract: the compiled-call exception
        path resets the donated pool and requeues every in-flight
        request (this one included) for deterministic replay."""
        geom, scfg = self.geom, self.scfg
        group = self._group_of(slot)
        alloc = self._allocators[group]
        if self._chaos is not None:
            try:
                self._chaos.maybe_fail("serve/prefill", key=req.rid,
                                       op="serve/prefill")
            except Exception:
                self._queue.appendleft(req)
                self._poison_rid = req.rid
                raise
        n_tok = len(req.prompt)
        shared, full_aligned, need, _resident = self._share_plan(req, group)
        if self._tiered:
            return self._admit_ctx_tiered(req, slot, shared, finished)
        priv = alloc.alloc(need)
        assert priv is not None  # _find_slot ran the same arithmetic
        if shared:
            alloc.share(shared)
        if full_aligned:
            # copy-on-write: the re-score must write position
            # n_tok - 1, which lives in the last shared page
            self._copy_page(group, shared[-1], priv[0])
            if self._tries is not None:
                self._tries[group].drop(alloc.free([shared[-1]]))
            pages = shared[:-1] + priv
            n_cached = n_tok - 1
            self._cow_pages += 1
        else:
            pages = shared + priv
            n_cached = len(shared) * geom.page_size
            n_cached += self._subpage_attach(req, group, len(shared),
                                             priv[0])
        self._shared_tokens += n_cached
        self._slots[slot] = _Slot(
            rid=req.rid, prompt=req.prompt, pages=pages, n_cached=n_cached,
            max_new=req.max_new, last_token=0, generated=[],
            pending=req.prompt[n_cached:], stop=req.stop_tokens,
        )
        self._prefill_count += 1
        if scfg.chunk_prefill == 0:
            # share-only mode: the tail drains inside the admission
            while (self._slots[slot] is not None
                   and self._slots[slot].pending):
                self._ctx_step([slot], finished)
        return True

    def _admit_ctx_tiered(self, req: Request, slot: int,
                          shared: list, finished: Optional[list]) -> bool:
        """The context admission across tiers: walk the matched chain
        attaching LIVE pages (refcount + 1) and RESTORING parked ones
        (warm-prefix hits — a fresh private device page filled from the
        host copy, the parked original retained for later sharers),
        then allocate the unshared footprint as lazy host reservations.
        A chain whose restore comes up short truncates there (the
        tail recomputes through the context program — correctness never
        depends on the cache); an allocation that comes up short
        unwinds and requeues for the next tick (the gate re-runs)."""
        geom, scfg = self.geom, self.scfg
        group = self._group_of(slot)
        alloc = self._allocators[group]
        n_tok = len(req.prompt)
        total = geom.pages_for(n_tok + req.max_new)

        def unwind(restored, live_taken):
            if restored:
                alloc.free(restored)
            if live_taken:
                alloc.free(live_taken)
            self._queue.appendleft(req)
            return False

        # 1. the chain: per matched block, a live page or a restore
        chain: list[int] = []      # page per block, in sequence order
        restored: list[int] = []
        for lp in shared:
            if alloc.refcount(lp) > 0:
                chain.append(lp)
                continue
            if not alloc.is_parked(lp):
                break  # evicted under us: the chain ends here
            fresh = self._tier_op(
                group,
                lambda p=lp: alloc.restore_parked(p, keep=restored),
            )
            if fresh is None:
                break  # no room to restore: prefill the rest instead
            chain.append(fresh)
            restored.append(fresh)
        m = len(chain)
        full_aligned = m > 0 and m * geom.page_size == n_tok
        last_live = full_aligned and chain[-1] not in restored

        # 2. the unshared footprint (+ the CoW page when the aligned
        # chain ends on a LIVE page — a restored tail is already
        # private); reserve pages are host-born, staged lazily
        priv_n = total - m + (1 if last_live else 0)
        priv = self._tier_op(
            group,
            lambda: alloc.alloc(priv_n, resident=1 if last_live else 0,
                                keep=chain),
        ) if priv_n else []
        if priv is None:
            return unwind(restored, [])
        live_pages = [lp for lp in chain if lp not in restored]
        if live_pages:
            alloc.share(live_pages)
        if restored:
            self.metrics.counter("serve/parked_restores").inc(
                len(restored)
            )

        # 3. seat the slot (the untiered cases, tier-resolved)
        if last_live:
            src = chain[-1]
            try:
                self._tier_op(
                    group,
                    lambda: alloc.ensure_resident([src], keep=priv[:1]),
                )
            except HostTierError:
                # even the degraded re-run found no device room for the
                # CoW source: give back everything this admission took
                # (the share() holds included) and retry from the queue
                # under device-only arithmetic
                return unwind(restored + priv, live_pages)
            self._copy_page(group, alloc.device_page(src),
                            alloc.device_page(priv[0]))
            alloc.mark_written(priv[:1])
            alloc.free([src])  # drop the hold share() just took
            pages = chain[:-1] + priv
            n_cached = n_tok - 1
            self._cow_pages += 1
        elif full_aligned:
            # last block restored: already private — re-score in place
            pages = chain + priv
            n_cached = n_tok - 1
        else:
            pages = chain + priv
            n_cached = m * geom.page_size
            n_cached += self._subpage_attach(req, group, m, priv[0])
        alloc.touch(pages)
        self._shared_tokens += n_cached
        self._slots[slot] = _Slot(
            rid=req.rid, prompt=req.prompt, pages=pages, n_cached=n_cached,
            max_new=req.max_new, last_token=0, generated=[],
            pending=req.prompt[n_cached:], stop=req.stop_tokens,
        )
        self._prefill_count += 1
        if scfg.chunk_prefill == 0:
            while (self._slots[slot] is not None
                   and self._slots[slot].pending):
                self._ctx_step([slot], finished)
        return True

    def _subpage_attach(self, req: Request, group: int, m: int,
                        target: int) -> int:
        """Sub-page (token-granular) sharing at the admission boundary
        (ISSUE 14, the PR-8 remainder): a prompt whose match ends
        MID-page copies the donor's boundary page into the admission's
        own first private page ``target`` at the token frontier — the
        full pages stay refcount-shared, the boundary tokens arrive by
        copy — so sharing is no longer quantized to ``page_size``.
        Returns the tokens attached (0 when no registered donor
        continues the ``m``-page match); the caller extends
        ``n_cached`` by it and the context program prefills only the
        remainder.

        The donor page is COPIED, never refcounted: the donor keeps
        writing its own page (its write frontier lives there) and the
        admission owns the copy outright, so no copy-on-write guard is
        ever needed on either side.  K/V at position ``j`` depends
        only on tokens ``[0, j]``, which donor and sharer agree on up
        to the frontier; entries past it are stale donor state that
        the length masks hide and this request's own writes — which
        start exactly at the frontier — overwrite (on the quantized
        rungs the first write also zeroes-past-offset and requantizes:
        the chunked-prefill write contract).  Capped at ``n_tok - 1``
        total shared tokens so the tail always re-scores at least one
        position for its logits."""
        if self._tries is None:
            return 0
        alloc = self._allocators[group]
        donor, n_sub = self._tries[group].match_tail(
            req.prompt, m, prefer=lambda p: alloc.refcount(p) > 0
        )
        n_sub = min(n_sub,
                    len(req.prompt) - 1 - m * self.geom.page_size)
        if donor is None or n_sub <= 0 or alloc.refcount(donor) < 1:
            return 0
        if self._tiered:
            try:
                self._tier_op(
                    group,
                    lambda: alloc.ensure_resident([donor, target]),
                )
            except HostTierError:
                return 0  # no device room: prefill the boundary instead
        self._copy_page(group, self._page_dev(group, donor),
                        self._page_dev(group, target))
        if self._tiered:
            alloc.mark_written([target])
            alloc.touch([donor, target])
        self._cow_pages += 1
        self._subpage_tokens += n_sub
        self.metrics.counter("serve/subpage_tokens").inc(n_sub)
        return n_sub

    def _ensure_private(self, slot: int, page_index: int) -> None:
        """Copy-on-write guard on the write paths: a slot about to
        write into table entry ``page_index`` must hold that page
        EXCLUSIVELY — if other requests share it, the payload is copied
        into a fresh page, the table entry swapped, and this slot's
        hold on the shared page dropped.  Unreachable in the supported
        admission flows (writes always land past the shared prefix;
        the full-aligned re-score pre-copies at admission), so a grant
        failure here is a logic error, not back-pressure."""
        st = self._slots[slot]
        group = self._group_of(slot)
        alloc = self._allocators[group]
        page = st.pages[page_index]
        if alloc.refcount(page) <= 1:
            return
        if self._tiered:
            self._tier_op(
                group, lambda: alloc.ensure_resident([page])
            )
            fresh = self._tier_op(
                group, lambda: alloc.alloc(1, resident=1, keep=[page])
            )
        else:
            fresh = alloc.alloc(1)
        if fresh is None:
            raise RuntimeError(
                f"copy-on-write of shared page {page} (slot {slot}) "
                "found an empty pool — admission reserved too little"
            )
        self._copy_page(group, self._page_dev(group, page),
                        self._page_dev(group, fresh[0]))
        if self._tiered:
            alloc.mark_written(fresh)
            alloc.touch(fresh)
        st.pages[page_index] = fresh[0]
        if self._tries is not None:
            self._tries[group].drop(alloc.free([page]))
        else:
            alloc.free([page])
        self._cow_pages += 1

    def _copy_page(self, group: int, src: int, dst: int) -> None:
        """Copy one page's payload (and, for quantized pools, its scale
        rows) between group-local DEVICE ids — the copy-on-write data
        move (tiered callers resolve logical ids first).  Host-level
        functional update between compiled steps; rare by construction
        (once per fully-shared aligned admission)."""
        off = group * self.geom.n_pages
        for name, buf in self._kv.items():
            self._kv[name] = buf.at[:, off + dst].set(buf[:, off + src])

    def _ctx_k_of(self, s: int) -> int:
        """Tokens the next context sweep advances for slot ``s`` — the
        wave planner's and stager's frontier width."""
        return max(1, min(self._chunk, len(self._slots[s].pending)))

    def _ctx_step(self, slots: list[int], finished: Optional[list]) -> None:
        """One context-prefill chunk for every PREFILLING slot, wave-
        partitioned under the tier (one wave — the whole set — when the
        device pool seats everything): each wave sweeps while the next
        wave's pages prefetch behind it."""
        waves = self._plan_waves(slots, self._ctx_k_of)
        for i, wave in enumerate(waves):
            nxt = waves[i + 1] if i + 1 < len(waves) else None
            self._ctx_sweep(wave, finished, prefetch=nxt)

    def _ctx_sweep(self, slots: list[int], finished: Optional[list],
                   prefetch: Optional[list] = None) -> None:
        """One context-prefill chunk for one wave of PREFILLING slots:
        each advances up to ``self._chunk`` pending prompt tokens
        through the ONE compiled context program (K/V written to its
        pages, ragged-causal attention over its cached prefix).  A slot
        whose pending tail drains samples its first token (the same
        ``request_key(seed, rid, 0)`` draw the monolithic prefill
        makes), registers its full prompt pages in the prefix trie, and
        joins the decode bank — or is evicted right here when its
        budget was one token."""
        scfg, geom = self.scfg, self.geom
        n, C = scfg.n_slots, self._chunk
        x = np.zeros((n, C, self.cfg.d_model), np.float32)
        tables = np.full((n, scfg.max_pages), geom.n_pages, np.int32)
        write_pages = np.full((n, C), geom.n_pages, np.int32)
        write_offs = np.zeros((n, C), np.int32)
        seq_lens = np.zeros((n,), np.int32)
        takes: dict[int, int] = {}
        for s in slots:
            st = self._slots[s]
            take = min(C, len(st.pending))
            takes[s] = take
            # CoW guard BEFORE the tables snapshot: a swapped page must
            # be what the program gathers
            for pi in range(st.n_cached // geom.page_size,
                            (st.n_cached + take - 1) // geom.page_size + 1):
                self._ensure_private(s, pi)
        # cold-hit fallback: pages the prefetch-ahead missed block here,
        # synchronously, before the table snapshot resolves device ids
        self._stage_wave(slots, self._ctx_k_of)
        for s in slots:
            st = self._slots[s]
            take = takes[s]
            group = self._group_of(s)
            x[s, :take] = self._embed_np[list(st.pending[:take])]
            row = self._sweep_row(group, st, take)
            tables[s, : len(row)] = row
            for j in range(take):
                pos = st.n_cached + j
                write_pages[s, j] = self._page_dev(
                    group, st.pages[pos // geom.page_size]
                )
                write_offs[s, j] = pos % geom.page_size
            seq_lens[s] = st.n_cached + 1
            if self._tiered:
                first = st.n_cached // geom.page_size
                last = (st.n_cached + take - 1) // geom.page_size
                self._allocators[group].mark_written(
                    st.pages[first:last + 1]
                )
        done = [s for s in slots
                if takes[s] == len(self._slots[s].pending)]
        try:
            with self.timeline.span("serve/prefill"):
                out, self._kv = self._ctx(
                    self.params, self._kv, jnp.asarray(x),
                    jnp.asarray(tables), jnp.asarray(write_pages),
                    jnp.asarray(write_offs), jnp.asarray(seq_lens),
                )
                if prefetch:
                    # double-buffered: the NEXT wave's pages land while
                    # this wave's compiled sweep runs (issued before the
                    # host sync below pulls its sampled tokens)
                    self._stage_wave(prefetch, self._ctx_k_of,
                                     best_effort=True, hold=tuple(slots))
                if done:
                    # STATIC shapes over the whole slot bank (the
                    # decode tick's rule): a variable done-set length
                    # would key fresh unembed/key/sample compiles mid-
                    # stream; idle rows sample with dummy keys, results
                    # discarded
                    last = np.zeros((n,), np.int64)
                    rids = np.zeros((n,), np.int32)
                    for s in done:
                        last[s] = takes[s] - 1
                        rids[s] = self._slots[s].rid
                    logits = self._unembed(
                        out[jnp.arange(n), jnp.asarray(last)], self.embed
                    )
                    keys = request_keys(
                        self._seed_key, jnp.asarray(rids),
                        jnp.zeros((n,), jnp.int32),
                    )
                    first = np.asarray(self._sample(keys, logits))
        except Exception:
            self._recover_cache()  # donated kv may be consumed; replay
            raise
        self._prefill_s += self._last_span_s()
        if self.tracer.enabled:
            self._trace_span([self._slots[s].rid for s in slots],
                             "prefill", chunked=True)
        for s in slots:
            st = self._slots[s]
            take = takes[s]
            st.n_cached += take
            st.pending = st.pending[take:]
            self._prefill_tokens += take
            self._fresh_tokens += take
        for s in done:
            st = self._slots[s]
            tok = int(first[s])
            st.last_token = tok
            st.generated = [tok]
            self._tokens_generated += 1
            self._mark_first_token(st.rid)
            if self._tries is not None:
                self._tries[self._group_of(s)].insert(st.prompt, st.pages)
            if self._done(st):
                out_pair = self._evict(s)
                if finished is not None:
                    finished.append(out_pair)

    def _done(self, st: _Slot) -> bool:
        """Finish rule, ONE definition for every sweep path: budget
        exhausted, or the last emitted token is one of the request's
        stop tokens (the stop token itself closes the output — it is
        emitted, then the slot finishes)."""
        return (len(st.generated) >= st.max_new
                or bool(st.stop and st.generated
                        and st.generated[-1] in st.stop))

    def _evict(self, slot: int) -> tuple[int, tuple[int, ...]]:
        st = self._slots[slot]
        assert st is not None
        self._free_slot_pages(slot, st)
        self._slots[slot] = None
        if self.tracer.enabled:  # THE terminal edge of every sweep path
            self.tracer.finish(st.rid, time.perf_counter())
        return st.rid, tuple(st.generated)

    # ---- the tick ------------------------------------------------------

    def step(self) -> list[tuple[int, tuple[int, ...]]]:
        """One engine tick: admit what fits, decode one token for every
        active slot, evict what finished.  Returns the finished
        ``(rid, tokens)`` pairs.  Each tick updates ``self.metrics``
        (tick latency, queue depth, free-page watermark, insert/evict
        counts, compile counts) and emits one sink event."""
        t0 = time.perf_counter()
        self._poison_rid = None
        prefills0 = self._prefill_count
        tokens0 = self._tokens_generated
        accepted0 = self._spec_accepted
        ptok0 = self._prefill_tokens
        finished = self._tick_inner()
        self._observe_tick(
            time.perf_counter() - t0,
            inserted=self._prefill_count - prefills0,
            evicted=len(finished),
            tokens=self._tokens_generated - tokens0,
            accepted=self._spec_accepted - accepted0,
            prefill_tokens=self._prefill_tokens - ptok0,
        )
        if self.tracer.enabled:
            # materialize finished trees now: the exact-decomposition
            # law (RequestTrace.check) asserts live at every tick end
            self.tracer.collect()
        return finished

    def _observe_tick(self, tick_s: float, inserted: int, evicted: int,
                      tokens: int, accepted: int = 0,
                      prefill_tokens: int = 0) -> None:
        m = self.metrics
        self._tick += 1
        free_min = min(a.n_free for a in self._allocators)
        m.histogram("serve/tick_s").observe(tick_s)
        m.gauge("serve/queue_depth").set(self.n_queued)
        m.gauge("serve/active_slots").set(self.n_active)
        # per-group minimum: Gauge.min is the run's free-page watermark,
        # the admission-control headroom signal
        m.gauge("serve/free_pages").set(free_min)
        m.counter("serve/inserts").inc(inserted)
        m.counter("serve/evictions").inc(evicted)
        m.counter("serve/tokens").inc(tokens)
        if prefill_tokens:
            # per-tick prefill compute: under chunked prefill its max is
            # bounded by chunk * slots — the p99-bounding claim as a
            # live histogram rather than a hope
            m.histogram("serve/prefill_tokens_tick").observe(prefill_tokens)
        if self.scfg.spec_k > 0:
            m.counter("serve/accepted").inc(accepted)
        if self._tiered:
            # tier residency telemetry (the PR-11 footprint idiom:
            # observable, not silent); cold_hits/cold_hit_s land where
            # they happen (_stage_wave) — these are the running totals
            m.gauge("serve/host_spilled_pages").set(self.host_spilled_pages)
            m.gauge("serve/host_prefetched_pages").set(
                self.host_prefetched_pages
            )
            m.gauge("serve/host_parked_pages").set(
                sum(a.n_parked for a in self._allocators)
            )
        m.gauge("serve/decode_compiles").set(self.decode_counter.count)
        m.gauge("serve/prefill_compiles").set(self.prefill_counter.count)
        if self.sink.enabled:  # skip the event build on the no-obs path
            self.sink.emit(
                "serve/tick",
                tick=self._tick, tick_s=round(tick_s, 6),
                queue_depth=self.n_queued, active=self.n_active,
                free_pages_min=free_min,
                inserted=inserted, evicted=evicted, tokens=tokens,
                accepted=accepted, prefill_tokens=prefill_tokens,
                decode_compiles=self.decode_counter.count,
                prefill_compiles=self.prefill_counter.count,
            )

    def _tick_inner(self) -> list[tuple[int, tuple[int, ...]]]:
        # collected finishes live on the ENGINE until the tick returns:
        # an admission that raises through mid-tick (retry_budget == 0)
        # must not lose requests evicted earlier in the same tick —
        # they were already freed from their slots, so the buffer is
        # the only place their tokens exist, and they re-emerge from
        # the next successful tick instead of vanishing
        finished = self._finish_buf
        if self._tiered:
            # advance the LRU clock and re-pin the hot window (each
            # live slot's write-frontier tail) before anything can spill
            for a in self._allocators:
                a.tick()
            self._update_pins()
        while self._queue:
            slot = self._find_slot(self._queue[0])
            if slot is None:
                break
            req = self._queue.popleft()
            if not self._admit(req, slot, finished):
                if self._queue and self._queue[0] is req:
                    # tiered admission fell short mid-plan (degrade or
                    # parked-eviction race) and requeued itself: stop
                    # admitting this tick — the gate re-runs next tick
                    break
                continue  # quarantined: the slot stays free
            st = self._slots[slot]
            # finished at prefill (budget of one, or the first token hit
            # a stop token); a chunked admission still prefilling is
            # evicted by _ctx_step later
            if (st is not None and not st.pending and st.generated
                    and self._done(st)):
                finished.append(self._evict(slot))
        if self._tiered:
            self._update_pins()  # fresh admissions joined the window

        # chunked prefill interleaves with decode INSIDE the tick: every
        # prefilling slot advances one chunk, every decoding slot one
        # token — a long admission costs each tick at most chunk tokens
        # of prefill instead of its whole prompt, which is what bounds
        # the resident streams' per-token p99
        prefilling = [s for s, st in enumerate(self._slots)
                      if st is not None and st.pending]
        if prefilling:
            self._ctx_step(prefilling, finished)
        active = [s for s, st in enumerate(self._slots)
                  if st is not None and not st.pending and st.generated]
        if active:
            # macro-first: since the host-free lift (ISSUE 19) a macro
            # width composes with speculation AND the tier — the scan
            # program drafts/verifies in-carry and waves prefetch behind
            # the running dispatch, so nothing falls back to per-token
            if self._macro_T > 1:
                self._macro_tick(active, finished)
            elif self.scfg.spec_k > 0:
                self._spec_tick(active, finished)
            else:
                self._decode_tick(active, finished)
        if self._tiered:
            self._prefetch_next_tick()
        self._finish_buf = []
        return finished

    def _prefetch_next_tick(self) -> None:
        """Schedule prefetch ONE TICK AHEAD from the page tables of the
        slots about to sweep: the first wave of the next tick's sweep
        set stages best-effort now, so in steady state the next tick's
        synchronous stage finds everything resident and a warm-path
        decode tick never blocks on a transfer (cold hits measure
        exactly the cases this missed)."""
        prefilling = [s for s, st in enumerate(self._slots)
                      if st is not None and st.pending]
        active = [s for s, st in enumerate(self._slots)
                  if st is not None and not st.pending and st.generated]
        if self._macro_T > 1:
            k_of = self._macro_k_of
        elif self.scfg.spec_k > 0:
            k_of = self._spec_k_of
        else:
            k_of = self._one
        nxt = prefilling + active
        if not nxt:
            return

        def k_mixed(s):
            return (self._ctx_k_of(s) if self._slots[s].pending
                    else k_of(s))

        waves = self._plan_waves(nxt, k_mixed)
        self._stage_wave(waves[0], k_mixed, best_effort=True)

    @staticmethod
    def _one(_s: int) -> int:
        """k_new for a plain decode sweep: one token per slot."""
        return 1

    def _decode_tick(self, active: list[int],
                     finished: list[tuple[int, tuple[int, ...]]]) -> None:
        """One plain decode tick, wave-partitioned under the tier (one
        wave — the whole bank — untiered or when everything fits):
        each wave's compiled sweep runs while the next wave's cold
        pages prefetch behind it (double-buffered; see
        ``serve.decode.plan_sweep_waves``)."""
        waves = self._plan_waves(active, self._one)
        for i, wave in enumerate(waves):
            nxt = waves[i + 1] if i + 1 < len(waves) else None
            self._decode_sweep(wave, finished, prefetch=nxt)
        self._decode_rounds += 1

    def _decode_sweep(self, active: list[int],
                      finished: list[tuple[int, tuple[int, ...]]],
                      prefetch: Optional[list] = None) -> None:
        """One plain decode sweep: one token per slot in this wave
        (slots outside it are masked idle — their streams depend only
        on their own pages and PRNG draws, so wave order cannot change
        any slot's output)."""
        scfg, geom = self.scfg, self.geom
        n = scfg.n_slots
        x = np.zeros((n, self.cfg.d_model), np.float32)
        tables = np.full((n, scfg.max_pages), geom.n_pages, np.int32)
        write_page = np.full((n,), geom.n_pages, np.int32)
        write_off = np.zeros((n,), np.int32)
        seq_lens = np.zeros((n,), np.int32)
        # idle slots keep (rid 0, pos 0): any key works, the draw is
        # discarded; one vectorized fold (request_keys) replaces ~3 tiny
        # dispatches per slot inside the latency-measured tick
        rids = np.zeros((n,), np.int32)
        positions = np.zeros((n,), np.int32)
        for s in active:
            st = self._slots[s]
            if self._tries is not None:  # CoW guard on the write target
                self._ensure_private(s, st.n_cached // geom.page_size)
        self._stage_wave(active, self._one)  # sync cold-hit fallback
        for s in active:
            st = self._slots[s]
            group = self._group_of(s)
            x[s] = self._embed_np[st.last_token]
            row = self._sweep_row(group, st, 1)
            tables[s, : len(row)] = row
            wp = st.pages[st.n_cached // geom.page_size]
            write_page[s] = self._page_dev(group, wp)
            write_off[s] = st.n_cached % geom.page_size
            seq_lens[s] = st.n_cached + 1
            rids[s] = st.rid
            positions[s] = len(st.generated)
            if self._tiered:
                self._allocators[group].mark_written([wp])
        try:
            with self.timeline.span("serve/decode"):
                out, self._kv = self._decode(
                    self.params, self._kv, jnp.asarray(x), jnp.asarray(tables),
                    jnp.asarray(write_page), jnp.asarray(write_off),
                    jnp.asarray(seq_lens),
                )
                if prefetch:
                    # double-buffered: the NEXT wave's pages land while
                    # this wave's compiled sweep runs (issued before the
                    # host sync below pulls the sampled tokens)
                    self._stage_wave(prefetch, self._one,
                                     best_effort=True, hold=tuple(active))
                keys = request_keys(self._seed_key, jnp.asarray(rids),
                                    jnp.asarray(positions))
                logits = self._unembed(out, self.embed)
                toks = np.asarray(self._sample(keys, logits))
        except Exception:
            self._recover_cache()  # donated kv may be consumed; replay
            raise
        self._decode_s += self._last_span_s()
        if self.tracer.enabled:
            self._trace_span([self._slots[s].rid for s in active],
                             "decode", rounds=1)
        self._decode_steps += 1
        self._dispatches += 1
        self._host_syncs += 1
        self._slot_steps += len(active)
        self._fresh_tokens += len(active)
        for s in active:
            st = self._slots[s]
            st.n_cached += 1
            st.last_token = int(toks[s])
            st.generated.append(st.last_token)
            self._tokens_generated += 1
            if self._done(st):
                finished.append(self._evict(s))

    def _macro_k_of(self, s: int) -> int:
        """k_new bound for a macro sweep's wave planning and staging:
        one dispatch advances a slot's write frontier by at most
        ``min(T * (spec_k + 1), remaining budget)`` tokens (each round
        emits at most ``draft_len + 1 <= remaining``, and ``remaining``
        bounds the whole scan — the admission-time page reservation
        stays valid), so staging this span past the cached frontier
        covers every page the dispatch can touch."""
        st = self._slots[s]
        return min(self._macro_T * (self.scfg.spec_k + 1),
                   st.max_new - len(st.generated))

    def _macro_tick(self, active: list[int],
                    finished: list[tuple[int, tuple[int, ...]]]) -> None:
        """One device-resident MACRO tick (ISSUE 15, host-free since
        ISSUE 19): up to ``macro_steps`` whole token rounds — or
        speculation rounds when ``spec_k > 0`` composes — for every
        active slot in ONE compiled ``lax.scan`` dispatch and ONE host
        sync.  Wave-partitioned under the tier exactly like
        ``_decode_tick`` (each wave's scan runs while the next wave's
        cold pages prefetch behind it), one wave — the whole bank —
        untiered."""
        waves = self._plan_waves(active, self._macro_k_of)
        rounds = 0
        for i, wave in enumerate(waves):
            nxt = waves[i + 1] if i + 1 < len(waves) else None
            rounds = max(rounds,
                         self._macro_sweep(wave, finished, prefetch=nxt))
        # token ROUNDS the bank ran this tick: waves partition SLOTS,
        # not rounds, so the bank-level count is the longest wave's
        # (the _decode_tick += 1 rule, scan-widened)
        self._decode_rounds += rounds

    def _macro_sweep(self, active: list[int],
                     finished: list[tuple[int, tuple[int, ...]]],
                     prefetch: Optional[list] = None) -> int:
        """One macro-scan dispatch for one wave: the scan carries page
        tables, write frontiers, lengths, PRNG fold-in positions,
        budget/stop done-masks — and under speculation the proposer's
        token-history window — on device (``serve.decode``'s
        ``build_decode_loop`` / ``build_spec_decode_loop``), so
        per-token AND per-round host orchestration disappear from the
        hot path.  Each scan iteration reproduces one legacy engine
        tick bit-for-bit; admission/eviction stay host-side at THIS
        boundary.

        The ASYNC macro tick (``scfg.async_macro``, plain path only):
        when the host has nothing to decide between scans — untiered,
        unshared, empty queue, no prefilling slot, no stop tokens in
        the wave — ALL remaining scans dispatch back-to-back, each fed
        the previous scan's device-side final carry, and the host syncs
        their token blocks once at the end: the halo driver's
        double-buffer idiom applied to the dispatch pipeline itself.
        Every chained scan has >= 1 active round (no stop tokens, and
        the chain length is ``ceil(max remaining / T)``), so the
        ``dispatches == ceil(slot_steps / T)`` identity is preserved
        exactly."""
        scfg, geom = self.scfg, self.geom
        n, T = scfg.n_slots, self._macro_T
        spec = self._spec_loop is not None
        tables = np.full((n, scfg.max_pages), geom.n_pages, np.int32)
        n_cached = np.zeros((n,), np.int32)
        rids = np.zeros((n,), np.int32)
        positions = np.zeros((n,), np.int32)
        budgets = np.zeros((n,), np.int32)
        last_tok = np.zeros((n,), np.int32)
        stop_mask = np.zeros((n, scfg.vocab), bool)
        stopped0 = np.zeros((n,), bool)
        emitted0 = np.zeros((n,), np.int32)
        hist = np.zeros((n, scfg.max_seq), np.int32) if spec else None
        for s in active:
            st = self._slots[s]
            if self._tries is not None:
                # CoW guard over the WHOLE write span up front (the
                # speculative sweep's rule): the scan's frontier may
                # cross into shared pages mid-dispatch, and the copy
                # must precede the tables snapshot
                for pi in range(st.n_cached // geom.page_size,
                                (st.n_cached + self._macro_k_of(s) - 1)
                                // geom.page_size + 1):
                    self._ensure_private(s, pi)
        self._stage_wave(active, self._macro_k_of)  # sync cold-hit path
        for s in active:
            st = self._slots[s]
            group = self._group_of(s)
            k_of = self._macro_k_of(s)
            row = self._sweep_row(group, st, k_of)
            tables[s, : len(row)] = row
            n_cached[s] = st.n_cached
            rids[s] = st.rid
            positions[s] = len(st.generated)
            budgets[s] = st.max_new - len(st.generated)
            last_tok[s] = st.last_token
            for t in st.stop:
                stop_mask[s, t] = True
            if spec:
                ctx = st.prompt + tuple(st.generated)
                hist[s, : len(ctx)] = ctx
            if self._tiered:
                first = st.n_cached // geom.page_size
                last = (st.n_cached + k_of - 1) // geom.page_size
                self._allocators[group].mark_written(
                    st.pages[first:last + 1]
                )
        n_scans = 1
        try:
            with self.timeline.span("serve/decode"):
                if spec:
                    toks_d, n_emit_d, dlen_d, self._kv = self._spec_loop(
                        self.params, self._kv, self.embed,
                        self._seed_key_data,
                        jnp.asarray(tables), jnp.asarray(n_cached),
                        jnp.asarray(rids), jnp.asarray(positions),
                        jnp.asarray(budgets), jnp.asarray(last_tok),
                        jnp.asarray(hist), jnp.asarray(stop_mask),
                        jnp.asarray(stopped0),
                    )
                    if prefetch:
                        # double-buffered: the NEXT wave's pages land
                        # while this wave's compiled scan runs
                        self._stage_wave(prefetch, self._macro_k_of,
                                         best_effort=True,
                                         hold=tuple(active))
                    # ONE host sync per T speculation rounds
                    toks = np.asarray(toks_d)
                    n_emit = np.asarray(n_emit_d)
                    dlen = np.asarray(dlen_d)
                else:
                    chain = (scfg.async_macro and not self._tiered
                             and self._tries is None and not self._queue
                             and prefetch is None
                             and not any(st is not None and st.pending
                                         for st in self._slots)
                             and all(not self._slots[s].stop
                                     for s in active))
                    if chain:
                        n_scans = max(
                            -(-int(budgets[s]) // T) for s in active
                        )
                    nc = jnp.asarray(n_cached)
                    po = jnp.asarray(positions)
                    lt = jnp.asarray(last_tok)
                    em = jnp.asarray(emitted0)
                    sp_ = jnp.asarray(stopped0)
                    tables_j = jnp.asarray(tables)
                    rids_j = jnp.asarray(rids)
                    budg_j = jnp.asarray(budgets)
                    stop_j = jnp.asarray(stop_mask)
                    toks_parts, mask_parts = [], []
                    for _ in range(n_scans):
                        (toks_d, mask_d, self._kv, nc, po, lt, em,
                         sp_) = self._decode_loop(
                            self.params, self._kv, self.embed,
                            self._seed_key_data, tables_j, nc, rids_j,
                            po, budg_j, lt, stop_j, sp_, em,
                        )
                        toks_parts.append(toks_d)
                        mask_parts.append(mask_d)
                    if prefetch:
                        self._stage_wave(prefetch, self._macro_k_of,
                                         best_effort=True,
                                         hold=tuple(active))
                    # ONE host sync per T tokens (per chained scan) —
                    # issued AFTER every scan in the chain dispatched
                    toks = np.concatenate(
                        [np.asarray(t) for t in toks_parts], axis=0
                    )
                    mask = np.concatenate(
                        [np.asarray(m) for m in mask_parts], axis=0
                    )
        except Exception:
            self._recover_cache()  # donated kv may be consumed; replay
            raise
        self._decode_s += self._last_span_s()
        self._decode_steps += n_scans
        self._dispatches += n_scans
        self._host_syncs += n_scans
        if spec:
            accept_hist = self.metrics.histogram("serve/accept_len")
            # rounds actually run before the early-exit psum idled the
            # bank (a round every slot skipped emitted nothing)
            rounds, occ = macro_occupancy(n_emit > 0)
            if self.tracer.enabled:
                # per-macro-tick decode occupancy, one span per rid
                # riding this scan, stamped with ITS round count
                sp_ev = self.timeline.spans[-1]
                for s in active:
                    self.tracer.work(self._slots[s].rid, "decode",
                                     sp_ev.begin, sp_ev.end,
                                     rounds=int(occ[s]), scans=n_scans)
            for s in active:
                st = self._slots[s]
                for r in range(n_emit.shape[0]):
                    ne = int(n_emit[r, s])
                    if ne == 0:
                        # active is monotone: later rounds are all idle
                        break
                    out = [int(t) for t in toks[r, s, :ne]]
                    st.generated.extend(out)
                    st.last_token = out[-1]
                    st.n_cached += ne
                    accept_hist.observe(ne - 1)
                    self._spec_drafted += int(dlen[r, s])
                    self._spec_accepted += ne - 1
                    self._slot_steps += 1
                    self._fresh_tokens += ne
                    self._tokens_generated += ne
                if self._done(st):
                    finished.append(self._evict(s))
        else:
            # rounds actually run before the early-exit mask idled the
            # bank (per-slot active masks are prefixes, so the longest
            # column IS the any-active iteration count)
            rounds, occ = macro_occupancy(mask)
            if self.tracer.enabled:
                sp_ev = self.timeline.spans[-1]
                for s in active:
                    self.tracer.work(self._slots[s].rid, "decode",
                                     sp_ev.begin, sp_ev.end,
                                     rounds=int(occ[s]), scans=n_scans)
            for s in active:
                st = self._slots[s]
                steps = int(occ[s])
                out = [int(t) for t in toks[:steps, s]]
                st.n_cached += steps
                st.generated.extend(out)
                if out:
                    st.last_token = out[-1]
                self._slot_steps += steps
                self._fresh_tokens += steps
                self._tokens_generated += steps
                if self._done(st):
                    finished.append(self._evict(s))
        return rounds

    def _spec_k_of(self, _s: int) -> int:
        """k_new bound for a speculative sweep: the full draft budget
        (the actual draft may be shorter — over-staging by at most one
        page, never under)."""
        return self.scfg.spec_k + 1

    def _spec_tick(self, active: list[int],
                   finished: list[tuple[int, tuple[int, ...]]]) -> None:
        """One speculative tick, wave-partitioned under the tier (see
        ``_decode_tick``)."""
        waves = self._plan_waves(active, self._spec_k_of)
        for i, wave in enumerate(waves):
            nxt = waves[i + 1] if i + 1 < len(waves) else None
            self._spec_sweep(wave, finished, prefetch=nxt)
        self._decode_rounds += 1

    def _spec_sweep(self, active: list[int],
                    finished: list[tuple[int, tuple[int, ...]]],
                    prefetch: Optional[list] = None) -> None:
        """One speculative sweep: every slot in this wave proposes up to
        ``spec_k`` self-drafted tokens (``propose_draft`` over its own
        prompt + generated history), the ONE verify forward scores the
        whole bank — each slot's cache pages gathered once for all its
        positions — and ``accept_speculative`` keeps the
        distribution-preserving prefix: ``a + 1`` tokens emitted per
        slot per sweep (``a`` accepted drafts + the terminal token),
        against ONE cache sweep instead of ``a + 1``.

        Rejected positions leave K/V garbage past the accepted frontier;
        the length masks hide it and the next sweep's writes (which
        start at the frontier and always cover at least as far)
        overwrite it — so speculation never dirties replayable state.
        The draft is clamped to the slot's remaining budget, keeping the
        page-footprint reservation made at admission valid."""
        scfg, geom = self.scfg, self.geom
        n, k = scfg.n_slots, scfg.spec_k
        K = k + 1
        x = np.zeros((n, K, self.cfg.d_model), np.float32)
        tables = np.full((n, scfg.max_pages), geom.n_pages, np.int32)
        write_pages = np.full((n, K), geom.n_pages, np.int32)
        write_offs = np.zeros((n, K), np.int32)
        seq_lens = np.zeros((n,), np.int32)
        drafts: dict[int, tuple[int, ...]] = {}
        for s in active:
            st = self._slots[s]
            remaining = st.max_new - len(st.generated)
            draft = propose_draft(
                st.prompt + tuple(st.generated), k, scfg.spec_ngram
            )[: remaining - 1]
            drafts[s] = draft
            if self._tries is not None:  # CoW guard on the write targets
                for pi in range(st.n_cached // geom.page_size,
                                (st.n_cached + len(draft))
                                // geom.page_size + 1):
                    self._ensure_private(s, pi)
        self._stage_wave(active, self._spec_k_of)  # sync cold-hit path
        for s in active:
            st = self._slots[s]
            group = self._group_of(s)
            toks = (st.last_token,) + drafts[s]
            x[s, : len(toks)] = self._embed_np[list(toks)]
            row = self._sweep_row(group, st, len(toks))
            tables[s, : len(row)] = row
            for j in range(len(toks)):
                pos = st.n_cached + j
                write_pages[s, j] = self._page_dev(
                    group, st.pages[pos // geom.page_size]
                )
                write_offs[s, j] = pos % geom.page_size
            seq_lens[s] = st.n_cached + 1
            if self._tiered:
                first = st.n_cached // geom.page_size
                last = (st.n_cached + len(toks) - 1) // geom.page_size
                self._allocators[group].mark_written(
                    st.pages[first:last + 1]
                )
        try:
            with self.timeline.span("serve/decode"):
                out, self._kv = self._decode(
                    self.params, self._kv, jnp.asarray(x), jnp.asarray(tables),
                    jnp.asarray(write_pages), jnp.asarray(write_offs),
                    jnp.asarray(seq_lens),
                )
                if prefetch:
                    self._stage_wave(prefetch, self._spec_k_of,
                                     best_effort=True, hold=tuple(active))
                logits = np.asarray(self._unembed(out, self.embed))
        except Exception:
            self._recover_cache()  # donated kv may be consumed; replay
            raise
        self._decode_s += self._last_span_s()
        if self.tracer.enabled:
            self._trace_span([self._slots[s].rid for s in active],
                             "decode", rounds=1, spec=True)
        self._decode_steps += 1
        self._dispatches += 1
        self._host_syncs += 1
        self._slot_steps += len(active)
        accept_hist = self.metrics.histogram("serve/accept_len")
        for s in active:
            st = self._slots[s]
            a, toks = accept_speculative(
                scfg.seed, st.rid, len(st.generated), logits[s], drafts[s],
                scfg.temperature, scfg.top_k,
            )
            if st.stop:
                # host-side EOS, the device rule mirrored: truncate the
                # emitted run at the first stop hit (the stop token
                # itself is kept); tokens past it were never emitted, so
                # the accepted count shrinks with the run — post-stop
                # K/V garbage follows the rejected-draft contract
                for j, t in enumerate(toks):
                    if t in st.stop:
                        toks = toks[: j + 1]
                        break
            a_eff = len(toks) - 1
            accept_hist.observe(a_eff)
            self._spec_drafted += len(drafts[s])
            self._spec_accepted += a_eff
            self._fresh_tokens += a_eff + 1
            st.n_cached += a_eff + 1
            st.generated.extend(toks)
            st.last_token = toks[-1]
            self._tokens_generated += len(toks)
            if self._done(st):
                finished.append(self._evict(s))

    def run(self, requests: Sequence[Request] = (),
            max_steps: int = 100_000) -> GenerateReport:
        """Submit ``requests`` and drain queue + slots to empty.  Counters
        in the report are THIS drain's deltas (compile counts stay
        engine-lifetime: that is what 'zero steady-state recompiles'
        means), so a reused engine's reports stay internally consistent
        — tokens_generated always reconciles with this run's outputs
        plus any requests already in flight at entry."""
        tokens0 = self._tokens_generated
        decode0, prefill0 = self._decode_steps, self._prefill_count
        prefill_s0, decode_s0 = self._prefill_s, self._decode_s
        slot0, drafted0 = self._slot_steps, self._spec_drafted
        accepted0 = self._spec_accepted
        ptok0, stok0 = self._prefill_tokens, self._shared_tokens
        fresh0, cow0 = self._fresh_tokens, self._cow_pages
        spill0, pref0 = self.host_spilled_pages, self.host_prefetched_pages
        cold0 = self._cold_hits
        sub0 = self._subpage_tokens
        disp0, hs0 = self._dispatches, self._host_syncs
        quarantined0 = set(self._quarantined)
        for r in requests:
            self.submit(r)
        outputs: dict[int, tuple[int, ...]] = {}
        steps = 0
        while self._queue or self.n_active:
            if steps >= max_steps:
                raise RuntimeError(
                    f"engine did not drain in {max_steps} steps "
                    f"({self.n_queued} queued, {self.n_active} active)"
                )
            for rid, toks in self.step():
                outputs[rid] = toks
            steps += 1
        report = self._report(outputs, tokens0, decode0, prefill0,
                              prefill_s0, decode_s0, slot0, drafted0,
                              accepted0,
                              tuple(sorted(set(self._quarantined)
                                           - quarantined0)),
                              ptok0, stok0, fresh0, cow0,
                              spill0, pref0, cold0, sub0=sub0,
                              disp0=disp0, hs0=hs0)
        self.sink.emit(
            "serve/report",
            completed=report.completed,
            tokens_generated=report.tokens_generated,
            decode_steps=report.decode_steps, prefills=report.prefills,
            decode_compiles=report.decode_compiles,
            prefill_compiles=report.prefill_compiles,
            prefill_s=round(report.prefill_s, 6),
            decode_s=round(report.decode_s, 6),
            quarantined=len(report.quarantined),
            slot_steps=report.slot_steps,
            dispatches=report.dispatches, host_syncs=report.host_syncs,
            drafted=report.drafted, accepted=report.accepted,
            prefill_tokens=report.prefill_tokens,
            shared_tokens=report.shared_tokens,
            cow_pages=report.cow_pages,
            fresh_kv_bytes=round(report.fresh_kv_bytes, 3),
            **({"spilled_pages": report.spilled_pages,
                "prefetched_pages": report.prefetched_pages,
                "cold_hits": report.cold_hits,
                "host_bytes": round(report.host_bytes, 3)}
               if self._tiered else {}),
        )
        emit_phase_totals(self.sink, self.recorder)
        self.sink.emit_metrics(self.metrics.snapshot(),
                               scope=self.metrics.id)
        self.sink.flush()
        return report

    def _report(self, outputs, tokens0, decode0, prefill0, prefill_s0,
                decode_s0, slot0=0, drafted0=0, accepted0=0,
                quarantined=(), ptok0=0, stok0=0, fresh0=0,
                cow0=0, spill0=0, pref0=0, cold0=0,
                sub0=0, disp0=0, hs0=0) -> GenerateReport:
        spilled = self.host_spilled_pages - spill0
        prefetched = self.host_prefetched_pages - pref0
        # per-request TTFT for requests completed this drain (rids the
        # router already consumed via take_ttft no longer appear)
        ttft = tuple(
            (rid, self._ttft.pop(rid))
            for rid in sorted(outputs) if rid in self._ttft
        )
        return GenerateReport(
            subpage_tokens=self._subpage_tokens - sub0,
            ttft_s=ttft,
            spilled_pages=spilled,
            prefetched_pages=prefetched,
            cold_hits=self._cold_hits - cold0,
            host_bytes=(spilled + prefetched) * (
                self.kv_page_bytes if self._tiered else 0.0
            ),
            completed=len(outputs),
            tokens_generated=self._tokens_generated - tokens0,
            decode_steps=self._decode_steps - decode0,
            prefills=self._prefill_count - prefill0,
            decode_compiles=self.decode_compiles,
            prefill_compiles=self.prefill_compiles,
            prefill_s=self._prefill_s - prefill_s0,
            decode_s=self._decode_s - decode_s0,
            outputs=tuple(sorted(outputs.items())),
            quarantined=tuple(quarantined),
            slot_steps=self._slot_steps - slot0,
            dispatches=self._dispatches - disp0,
            host_syncs=self._host_syncs - hs0,
            drafted=self._spec_drafted - drafted0,
            accepted=self._spec_accepted - accepted0,
            prefill_tokens=self._prefill_tokens - ptok0,
            shared_tokens=self._shared_tokens - stok0,
            cow_pages=self._cow_pages - cow0,
            fresh_kv_bytes=(self._fresh_tokens - fresh0)
            * self.kv_bytes_per_token,
        )
