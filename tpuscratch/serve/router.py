"""Fleet router: prefix-affine load balancing, per-tenant SLO classes,
and an autoscaled prefill:decode pool over N engine replicas.

Everything below one engine is built (paged int8/fp8 KV, fused paged
attention, speculative decode, prefix sharing, chunked prefill,
prefill/decode disaggregation, tiered host KV); "millions of users"
means **many** engines, and without a front end every replica is an
island — a request that lands on the wrong replica re-prefills a
prefix another replica already holds, and nothing trades TTFT against
tokens/s per tenant.  :class:`FleetRouter` owns the fleet-level queue
and dispatches across N :class:`~tpuscratch.serve.engine.ServeEngine`
/ :class:`~tpuscratch.serve.disagg.DisaggEngine` replicas, four layers
deep:

1. **prefix-affine routing** — the SOSP '23 paged-KV sharing argument
   applied ACROSS replicas: the router keeps a fleet-level prefix
   index (page-aligned prompt-prefix blocks -> the replicas routed
   requests with that prefix, PLUS each replica's live
   ``PrefixCache``/parked-chain state read through
   ``prefix_match_tokens``) and sends each request to the replica
   holding its longest matched prefix, falling back to least-loaded.
   Static counters prove the savings: over a fault-free drain, fleet
   ``prefill_tokens + shared_tokens == submitted prompt tokens``, and
   ``prefill_frac`` drops monotonically as affinity concentrates
   tenants (``RouterReport``).
2. **per-tenant SLO classes** — requests carry a tenant/class tag
   (:class:`SLOClass`): TTFT-target classes prefer chunked-prefill
   replicas (admission never monopolizes a tick), throughput classes
   prefer resident (unchunked) scheduling, and ``max_queue`` bounds a
   class's in-flight depth per replica — the backpressure knob.  The
   engines stamp per-request TTFT (``GenerateReport.ttft_s`` /
   ``take_ttft``), so the router reports per-class p50/p99 TTFT and
   tokens/s — the MegaScale goodput-accounting discipline applied to
   fleet scheduling.
3. **autoscaled prefill:decode ratio** (disagg fleets) — replicas
   re-role between the PREFILL pool (accepts new dispatches) and the
   DECODE pool (drains only) from the staged-handoff backlog: a deep
   backlog means decode is the bottleneck, so the router shrinks the
   prefill pool; a dry one grows it back.  Hysteresis (two
   thresholds + a cooldown) keeps re-roling from thrashing, and the
   prefill pool never empties.
4. **sub-page sharing** rides below (serve/engine ``_subpage_attach``):
   a matched prefix ending mid-page shares its full pages and copies
   the boundary page at the token frontier, so affinity wins are no
   longer quantized to ``page_size``.
5. **fleet-scale chaos** (ISSUE 17) — a :class:`~tpuscratch.ft.chaos.
   ChaosPlan` passed at construction is queried once per (fleet tick,
   replica) at site ``serve/replica``: ``kind="kill"`` tears a whole
   replica down mid-stream (``ServeEngine.evacuate``) and the router
   RE-ADMITS its in-flight + queued requests at the head of the fleet
   queue from its own pending records (original submit stamps kept, so
   the outage is IN the reported TTFT), with the replica re-joining
   empty after ``down_ticks``; ``kind="stall"`` freezes the replica
   without losing state.  Zero requests are dropped, replay is
   bit-identical (rids key the PRNG streams), and the counter law
   generalizes: ``prefill + shared == submitted + readmitted_tokens``
   — each re-admitted leg recomputes exactly the prompt tokens the
   dead replica had already accounted.  The wasted legs plus the
   generated tokens that died with the pool feed the per-class
   goodput fraction (the MegaScale NSDI '24 accounting under churn).
6. **SLO-aware load shedding** (ISSUE 18) — backpressure that can say
   NO: each :class:`SLOClass` carries a queue-deadline budget
   (``shed_after_s``) and a fleet-wide open-set cap (``max_open``);
   instead of holding forever, the router sheds the lowest-class /
   most-deadline-blown ROUTER-QUEUED work with an explicit
   :class:`RequestShed` outcome (``take_shed``), displacement-first so
   top-class work never sheds while a lower class has queued work to
   give up.  The request-count law ``submitted == finished + shed +
   open`` holds at every tick; shed prompts leave the token law's
   submitted leg (they never prefill) and charge the shedding class's
   goodput fraction.  ``RouterConfig.tick_s`` puts shed deadlines on
   the logical fleet-tick clock so WHICH requests shed is a pure
   function of the trace — repeat storms stay digest-identical.

House invariant: greedy output is BIT-identical under any routing —
1 replica or N, affinity on or off, any re-roling schedule, any
replica-kill schedule — because a request's stream depends only on
``(seed, rid, prompt)``: sampling keys are
``request_key(seed, rid, position)`` draws and every engine path
(share/spec/chunk/disagg/tiered, fp32/int8/fp8) is test-gated
batch-composition-independent.  Routing moves WHERE work runs and
WHAT is recomputed, never what is emitted (tests/test_serve_router.py,
marker ``router``; tests/test_traffic.py, marker ``traffic``).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Optional, Sequence, Union

from tpuscratch.ft.chaos import ChaosPlan, bind_tracer
from tpuscratch.obs.metrics import Reservoir, percentile
from tpuscratch.obs.reqtrace import NullReqTracer
from tpuscratch.serve.disagg import DisaggEngine
from tpuscratch.serve.engine import Request, ServeEngine


def _percentile(xs: Sequence[float], q: float) -> float:
    """``obs.metrics.percentile`` (the ONE nearest-rank
    implementation), tolerating an empty drain (0 completions in a
    class) as 0.0 instead of raising."""
    return percentile(xs, q) if xs else 0.0


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One tenant/traffic class and its service objective.

    ``target="ttft"`` maps the class to chunked-prefill admission
    (replicas with ``chunk_prefill > 0`` preferred — a long admission
    never monopolizes a tick, bounding time-to-first-token);
    ``target="throughput"`` maps it to resident scheduling (unchunked
    replicas preferred — no per-chunk overhead on the prefill path).
    On a homogeneous fleet the preference is vacuous and every replica
    is a candidate.  ``max_queue`` bounds the class's dispatched-but-
    unfinished depth PER replica (0 = unbounded): when every candidate
    is at the bound the request holds in the router queue — per-class
    backpressure instead of unbounded replica queues.

    Overload control (ISSUE 18): ``shed_after_s`` is the class's
    deadline budget — router-queued work older than this SHEDS instead
    of holding forever (0 = hold forever, the pre-ISSUE-18 behavior).
    A deadline-blown request first looks for a STRICTLY lower-priority
    queued victim to displace (priority = position in
    ``RouterConfig.classes``, index 0 highest), so top-class work never
    sheds while a lower class has queued work to give up.  ``max_open``
    caps the class's OPEN set (router-queued + in-flight, fleet-wide,
    0 = unbounded): exceeding it sheds the lowest-priority queued work
    — the overload pressure valve.  Only router-QUEUED work ever
    sheds; dispatched work always completes (no computed tokens are
    thrown away)."""

    name: str
    target: str = "throughput"   # "ttft" | "throughput"
    max_queue: int = 0           # per-replica in-flight bound, 0 = off
    shed_after_s: float = 0.0    # queue-wait deadline budget, 0 = hold
    max_open: int = 0            # fleet-wide open-set cap, 0 = unbounded

    def __post_init__(self):
        if self.target not in ("ttft", "throughput"):
            raise ValueError(
                f"SLO target {self.target!r} not in ('ttft', 'throughput')"
            )
        if self.max_queue < 0:
            raise ValueError(
                f"max_queue must be >= 0, got {self.max_queue}"
            )
        if self.shed_after_s < 0:
            raise ValueError(
                f"shed_after_s must be >= 0, got {self.shed_after_s}"
            )
        if self.max_open < 0:
            raise ValueError(
                f"max_open must be >= 0, got {self.max_open}"
            )


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Fleet knobs (the engines themselves come from ``ServeConfig``)."""

    affinity: bool = True        # prefix-affine routing (else least-loaded)
    classes: tuple[SLOClass, ...] = (SLOClass("default"),)
    # disagg-fleet autoscaling: re-role replicas between the prefill
    # and decode pools from the staged-handoff backlog (requests
    # prefilled but waiting for decode slots).  Backlog per prefill
    # replica > scale_down_backlog: decode is the bottleneck — move a
    # prefill replica to the decode pool; < scale_up_backlog: prefill
    # is starving decode — move one back.  The gap between the two
    # thresholds plus cooldown_ticks is the hysteresis band that keeps
    # re-roling from thrashing; the prefill pool never drops below one
    # replica.
    autoscale: bool = False
    scale_down_backlog: float = 4.0   # staged per prefill replica, upper
    scale_up_backlog: float = 1.0     # staged per prefill replica, lower
    cooldown_ticks: int = 4
    # fleet prefix-index size bound (page-aligned block keys); oldest
    # entries evict first — staleness only costs a routing choice,
    # never correctness
    index_cap: int = 4096
    # replica chaos (ISSUE 17): default outage length in fleet ticks
    # for a serve/replica kill/stall whose Fault has no down_ticks —
    # the elastic re-join happens this many ticks after the fault
    rejoin_ticks: int = 8
    # per-class TTFT reservoir size: bounded-memory tails over a
    # stream-scale drain (exact whenever a drain completes fewer
    # requests than this — every pre-ISSUE-17 report is bit-equal)
    ttft_reservoir: int = 4096
    # shed-deadline clock (ISSUE 18): > 0 makes queue-wait age a
    # LOGICAL quantity — (fleet ticks held) × tick_s — so the shed
    # schedule is a pure function of the trace and replica speed never
    # changes WHICH requests shed (repeat runs stay digest-identical).
    # 0 = wall-clock age (deadlines mean real seconds).  TTFT stays
    # wall-clock either way.
    tick_s: float = 0.0

    def __post_init__(self):
        if not self.classes:
            raise ValueError("RouterConfig needs at least one SLOClass")
        names = [c.name for c in self.classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO class names: {names}")
        if self.scale_up_backlog >= self.scale_down_backlog:
            raise ValueError(
                "hysteresis band inverted: scale_up_backlog "
                f"{self.scale_up_backlog} must be < scale_down_backlog "
                f"{self.scale_down_backlog}"
            )
        if self.cooldown_ticks < 0:
            raise ValueError(
                f"cooldown_ticks must be >= 0, got {self.cooldown_ticks}"
            )
        if self.index_cap < 1:
            raise ValueError(f"index_cap must be >= 1, got {self.index_cap}")
        if self.rejoin_ticks < 1:
            raise ValueError(
                f"rejoin_ticks must be >= 1, got {self.rejoin_ticks}"
            )
        if self.ttft_reservoir < 1:
            raise ValueError(
                f"ttft_reservoir must be >= 1, got {self.ttft_reservoir}"
            )
        if self.tick_s < 0:
            raise ValueError(f"tick_s must be >= 0, got {self.tick_s}")


@dataclasses.dataclass(frozen=True)
class ClassReport:
    """One SLO class's drain: completion, TTFT tail, token rate —
    plus the churn accounting (ISSUE 17).  The TTFT percentiles come
    from a bounded :class:`~tpuscratch.obs.metrics.Reservoir` (exact
    while ``ttft_exact``; a uniform whole-drain sample past
    ``RouterConfig.ttft_reservoir`` completions).  ``goodput_frac`` is
    the MegaScale-style useful-work fraction: tokens the tenant got
    (final-leg prompts + delivered outputs) over everything the fleet
    computed for the class, including re-admitted prefill legs,
    generated tokens that died with a killed replica, and — ISSUE 18 —
    prompt tokens the class submitted and then SHED (the tenant asked
    and got nothing; shed waste is charged to the shedding class) —
    1.0 exactly on a chaos-free, shed-free drain."""

    name: str
    completed: int
    tokens: int
    ttft_p50_s: float
    ttft_p99_s: float
    tokens_per_s: float
    ttft_exact: bool = True
    readmitted: int = 0
    goodput_frac: float = 1.0
    shed: int = 0                # requests shed from this class
    shed_tokens: int = 0         # their prompt tokens (never computed)


@dataclasses.dataclass(frozen=True)
class RouterReport:
    """What a fleet drain produced — ``GenerateReport``'s router twin.

    The static sharing proof holds fleet-wide on a fault-free drain:
    ``prefill_tokens + shared_tokens == submitted_prompt_tokens`` —
    every submitted prompt token was either COMPUTED through some
    replica's prefill program or SERVED from a shared page — so
    ``prefill_frac`` dropping under affinity is arithmetic, not a
    measurement.  Under replica churn (ISSUE 17) the law generalizes
    exactly: ``prefill + shared == submitted + readmitted_tokens``,
    where ``readmitted_tokens`` counts, per re-admitted victim, the
    prompt tokens its dead replica had already accounted (the extra
    leg the final drain computes again).  (A disagg handoff that
    degrades to a local re-prefill double-counts its prompt;
    chaos-free non-degraded drains reconcile exactly.)"""

    completed: int
    tokens_generated: int
    wall_s: float
    tokens_per_s: float                  # aggregate, fleet-wide
    outputs: tuple[tuple[int, tuple[int, ...]], ...]
    classes: tuple[ClassReport, ...]
    # the fleet prefill-counter law's three legs
    prefill_tokens: int
    shared_tokens: int
    submitted_prompt_tokens: int
    subpage_tokens: int = 0
    # routing accounting
    affinity_hits: int = 0       # dispatches that followed a prefix match
    affinity_tokens: int = 0     # matched prefix tokens at dispatch time
    backpressure_holds: int = 0  # dispatch attempts held by max_queue
    reroles: int = 0             # prefill<->decode pool moves
    dispatched: tuple[int, ...] = ()  # requests per replica
    # fleet decode-side dispatch accounting (ISSUE 15): compiled decode
    # invocations + token host-syncs summed across replicas — under
    # macro-step replicas (``ServeConfig(macro_steps=T)``) both drop
    # ~T× at fixed token count; per single-stream replica the identity
    # dispatches == ceil(slot_steps / macro_steps) holds exactly
    # (asserted live in ex32).  Since the host-free lift (ISSUE 19)
    # macro replicas compose with spec_k/kv_host_pages too — a fleet of
    # speculating or tiered replicas keeps the same ~T× drop (up to
    # T·(spec_k+1) token rounds per dispatch under speculation).
    # Lower-is-better in obs.regress.
    dispatches: int = 0
    host_syncs: int = 0
    # replica-chaos accounting (ISSUE 17): kills/stalls are the churn
    # the drain survived; readmitted counts re-admitted request legs
    # (zero requests may be DROPPED — the dropped counter exists to be
    # asserted zero: only a killed replica holding rids the router
    # never routed, i.e. predispatched behind its back, can drop)
    kills: int = 0
    stalls: int = 0
    readmitted: int = 0
    readmitted_tokens: int = 0   # re-prefilled legs (the law's 4th term)
    lost_tokens: int = 0         # generated tokens that died with a pool
    dropped: int = 0
    # overload shedding (ISSUE 18): requests the router gave an
    # explicit RequestShed outcome instead of holding forever.  Their
    # prompts are EXCLUDED from submitted_prompt_tokens (they never
    # prefill), keeping the token law exact under shedding; the
    # request-count law is submitted == finished + shed + open at
    # every tick (live properties on the router).
    shed: int = 0
    shed_tokens: int = 0

    @property
    def prefill_frac(self) -> float:
        """Fraction of submitted prompt tokens actually prefilled —
        the quantity cross-replica affinity exists to shrink."""
        total = self.prefill_tokens + self.shared_tokens
        return self.prefill_tokens / total if total else 1.0

    @property
    def shared_frac(self) -> float:
        total = self.prefill_tokens + self.shared_tokens
        return self.shared_tokens / total if total else 0.0


@dataclasses.dataclass(frozen=True)
class RequestShed:
    """One shed outcome — the router's explicit "no" (ISSUE 18).
    ``reason`` is ``"deadline"`` (blew its own ``shed_after_s`` with no
    lower class to displace), ``"displaced"`` (gave way to a
    deadline-blown higher class), or ``"over_open"`` (a class exceeded
    ``max_open``).  ``waited_s`` is the queue age at shed time, on the
    same clock the deadline used (logical under ``tick_s``).  The rid
    is free to be RE-submitted — a shed clears it from the router's
    seen-set, so a closed-loop client's seeded retry replays the same
    (rid, prompt) and emits the same tokens wherever it finally
    lands."""

    rid: int
    cls: str
    reason: str
    waited_s: float


@dataclasses.dataclass
class _Pending:
    """One routed-but-not-yet-dispatched request.  ``t0`` is the
    ROUTER-submit wall stamp: the TTFT clock starts here, so time held
    in the router queue (backpressure, candidate filtering) counts
    toward the per-class TTFT the router reports — ``max_queue`` must
    never look free in the SLO report.  ``tick`` is the fleet-tick
    twin: the logical submit stamp the shed deadline ages against when
    ``RouterConfig.tick_s`` is set."""

    cls: str
    req: Request
    t0: float = 0.0
    tick: int = 0


class FleetRouter:
    """Front end over N engine replicas: ``submit`` tags and queues,
    ``step`` dispatches + ticks every replica, ``run`` drains.

    Replicas must be output-compatible — same sampling seed, vocab,
    ``max_seq``, temperature/top-k, page size, and KV dtype — so a
    request emits the SAME tokens wherever it lands (checked at
    construction; scheduling knobs like ``n_slots``/``chunk_prefill``
    may differ per replica, and a heterogeneous chunked/unchunked mix
    is exactly how the SLO classes get their two admission paths).
    Every replica steps every tick (a decode-pool replica keeps
    draining); only DISPATCH is role-gated — and a DOWN replica
    (killed or stalled by a ``serve/replica`` chaos fault) neither
    steps nor receives dispatches until its outage window elapses."""

    def __init__(self, replicas: Sequence[Union[ServeEngine, DisaggEngine]],
                 rcfg: Optional[RouterConfig] = None,
                 chaos: Optional[ChaosPlan] = None,
                 tracer=None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas = list(replicas)
        self.rcfg = rcfg or RouterConfig()
        self._chaos = chaos
        # ONE shared per-request tracer (obs.reqtrace) across the router
        # and every replica, so a request's span tree stays whole as it
        # moves between layers; None leaves each replica's own tracer
        # (NullReqTracer by default) untouched
        self.tracer = tracer if tracer is not None else NullReqTracer()
        if tracer is not None:
            bind_tracer(chaos, tracer)
            for r in self.replicas:
                r.set_tracer(tracer)
        if chaos is not None and any(
            f.site == "serve/replica" and f.kind == "kill"
            for f in chaos.faults
        ) and any(not hasattr(r, "evacuate") for r in self.replicas):
            raise ValueError(
                "serve/replica kill faults need replicas exposing "
                "evacuate() (ServeEngine and DisaggEngine both do)"
            )
        ref = self._scfg(self.replicas[0])
        for r in self.replicas[1:]:
            sc = self._scfg(r)
            for f in ("seed", "vocab", "max_seq", "temperature", "top_k",
                      "page_size", "kv_dtype"):
                if getattr(sc, f) != getattr(ref, f):
                    raise ValueError(
                        f"replica ServeConfig.{f} mismatch "
                        f"({getattr(sc, f)!r} != {getattr(ref, f)!r}): "
                        "outputs would depend on routing"
                    )
        self._page = ref.page_size
        self._classes = {c.name: c for c in self.rcfg.classes}
        self._disagg = all(isinstance(r, DisaggEngine)
                           for r in self.replicas)
        if self.rcfg.autoscale and not self._disagg:
            raise ValueError(
                "autoscale re-roles prefill:decode pools — every "
                "replica must be a DisaggEngine"
            )
        self._queue: collections.deque[_Pending] = collections.deque()
        self._class_of: dict[int, str] = {}      # rid -> class name
        self._replica_of: dict[int, int] = {}    # rid -> replica index
        self._inflight: set[int] = set()         # dispatched, unfinished
        # rid -> its _Pending while dispatched-but-unfinished: the
        # re-admission record a replica kill re-queues from (bounded by
        # in-flight depth, not trace length — the byte budget holds)
        self._pending_of: dict[int, _Pending] = {}
        self._seen: set[int] = set()
        # per-(replica, class) dispatched-but-unfinished depth — the
        # backpressure quantity max_queue bounds
        self._depth: dict[tuple[int, str], int] = {}
        # fleet prefix index: (aligned_len, rolling_hash) block key ->
        # replica ids in registration order (insertion-ordered dict
        # doubles as the LRU-ish eviction order under index_cap)
        self._index: dict[tuple[int, int], list[int]] = {}
        # pool roles (autoscale): True = accepts new dispatches
        self._prefill_role = [True] * len(self.replicas)
        self._cooldown = 0
        # replica chaos (ISSUE 17): fleet tick counter (the chaos
        # schedule's occurrence index) and per-replica outage windows
        self._tick = 0
        self._down = [0] * len(self.replicas)
        # run()-scoped accounting (lifetime counters, deltas at run)
        self._submitted_ptok = 0
        self._affinity_hits = 0
        self._affinity_tokens = 0
        self._backpressure_holds = 0
        self._reroles = 0
        self._kills = 0
        self._stalls = 0
        self._readmitted = 0
        self._readmitted_tokens = 0
        self._lost_tokens = 0
        self._dropped = 0
        self._dispatched = [0] * len(self.replicas)
        names = [c.name for c in self.rcfg.classes]
        # overload shedding (ISSUE 18): class priority = position in
        # rcfg.classes (0 = top); request-count law counters
        # (submitted == finished + shed + open at every tick) and the
        # shed outcome log drained via take_shed()
        self._prio = {c.name: i for i, c in enumerate(self.rcfg.classes)}
        self._shed_enabled = any(
            c.shed_after_s > 0 or c.max_open > 0 for c in self.rcfg.classes
        )
        self._submitted = 0
        self._finished = 0
        self._shed = 0
        self._shed_ptok = 0
        self._open_by_class: dict[str, int] = {n: 0 for n in names}
        self._class_shed: dict[str, int] = {n: 0 for n in names}
        self._class_shed_tok: dict[str, int] = {n: 0 for n in names}
        self._shed_log: list[RequestShed] = []
        self._ttft: dict[str, Reservoir] = {}
        self._reset_ttft()
        self._class_tokens: dict[str, int] = {n: 0 for n in names}
        self._class_done: dict[str, int] = {n: 0 for n in names}
        self._class_ptok: dict[str, int] = {n: 0 for n in names}
        self._class_readmitted: dict[str, int] = {n: 0 for n in names}
        self._class_readm_tok: dict[str, int] = {n: 0 for n in names}
        self._class_lost: dict[str, int] = {n: 0 for n in names}

    def _reset_ttft(self) -> None:
        """Fresh per-class TTFT reservoirs — a drain window's tails
        are THIS drain's (the prior per-request-list slicing semantics,
        now in bounded memory); seeds are fixed per class so the same
        drain reports the same percentiles."""
        for ci, c in enumerate(self.rcfg.classes):
            self._ttft[c.name] = Reservoir(
                k=self.rcfg.ttft_reservoir, seed=ci
            )

    @staticmethod
    def _scfg(replica):
        return replica.scfg

    # ---- introspection --------------------------------------------------

    @property
    def n_queued(self) -> int:
        """Router-held requests plus every replica's own queue."""
        return len(self._queue) + sum(r.n_queued for r in self.replicas)

    @property
    def n_active(self) -> int:
        return sum(r.n_active for r in self.replicas)

    @property
    def n_prefill_pool(self) -> int:
        return sum(self._prefill_role)

    @property
    def reroles(self) -> int:
        return self._reroles

    # ---- the request-count law (ISSUE 18) -------------------------------
    # submitted == finished + shed + open, at EVERY fleet tick: every
    # request the router accepted is exactly one of completed (incl.
    # quarantined-terminal), explicitly shed, or still open (router-
    # queued or in-flight).  Lifetime counters — harnesses assert the
    # law live, per tick, not just at drain.

    @property
    def submitted_requests(self) -> int:
        return self._submitted

    @property
    def finished_requests(self) -> int:
        return self._finished

    @property
    def shed_requests(self) -> int:
        return self._shed

    @property
    def open_requests(self) -> int:
        """Router-queued + dispatched-but-unfinished — the law's open
        term, and the bounded-queue quantity overload control exists
        to bound."""
        return len(self._queue) + len(self._inflight)

    def take_shed(self) -> list[RequestShed]:
        """Drain the shed outcomes since the last call — the closed
        loop's retry trigger (the engine ``take_*`` idiom)."""
        out, self._shed_log = self._shed_log, []
        return out

    # ---- request lifecycle ----------------------------------------------

    def submit(self, req: Request, tenant: str = "default") -> None:
        """Queue ``req`` under ``tenant``'s SLO class.  rids are unique
        FLEET-wide (they key the PRNG streams and the merged outputs
        map — the engine's rule, one level up)."""
        if tenant not in self._classes:
            raise ValueError(
                f"unknown tenant class {tenant!r} "
                f"(have {sorted(self._classes)})"
            )
        # EVERY replica's admission rules, enforced at THIS front door
        # (routing may send the request anywhere): a request a replica
        # would reject must fail here, not raise out of a later
        # dispatch — where it would stay router-queued and re-raise
        # every tick.  The common rules run ONCE (the constructor pins
        # every validate_request-relevant field fleet-equal); only each
        # replica's local half (e.g. the disagg staging bound) loops.
        self.replicas[0].validate(req)
        for r in self.replicas[1:]:
            r.validate_local(req)
        if req.rid in self._seen:
            raise ValueError(f"request id {req.rid} already used")
        self._seen.add(req.rid)
        self._class_of[req.rid] = tenant
        self._submitted_ptok += len(req.prompt)
        self._class_ptok[tenant] += len(req.prompt)
        self._submitted += 1
        self._open_by_class[tenant] += 1
        t0 = time.perf_counter()
        self.tracer.begin(req.rid, t0, cls=tenant)
        self._queue.append(_Pending(cls=tenant, req=req, t0=t0,
                                    tick=self._tick))

    # ---- the fleet prefix index -----------------------------------------

    def _block_keys(self, prompt: tuple) -> list[tuple[int, int]]:
        """``(aligned_length, rolling_hash)`` per page-aligned prefix of
        ``prompt`` — ONE O(len) pass, instead of materializing and
        hashing O(len²/page_size) prefix-tuple tokens per probe (the
        long-context dispatch cost).  The polynomial hash is
        deterministic across processes (no PYTHONHASHSEED), and a
        collision merely merges two prefix families in the PLANNED
        index — it costs a routing choice, never correctness (the
        index's standing discipline)."""
        keys = []
        h = 0
        for j, t in enumerate(prompt):
            h = (h * 1_000_003 + t + 1) & 0xFFFFFFFFFFFFFFFF
            if (j + 1) % self._page == 0:
                keys.append((j + 1, h))
        return keys

    def _register(self, keys: list[tuple[int, int]], replica: int) -> None:
        """Record the dispatch in the fleet index: every page-aligned
        prefix block (``_block_keys`` form) -> this replica.  PLANNED
        state, not live state — it makes same-prefix followers route
        together even before the first prefill lands (the live tries
        are empty exactly when the burst arrives); the live half of the
        score comes from ``prefix_match_tokens`` at dispatch time.
        Stale entries (the replica's pages died) cost a routing choice,
        never correctness, and the cap evicts oldest-first."""
        for key in keys:
            reps = self._index.get(key)
            if reps is None:
                if len(self._index) >= self.rcfg.index_cap:
                    self._index.pop(next(iter(self._index)))
                self._index[key] = [replica]
            elif replica not in reps:
                reps.append(replica)

    def _planned_match(self, keys: list[tuple[int, int]],
                       replica: int) -> int:
        """Longest indexed page-aligned prefix (tokens) this replica
        was already routed, from the prompt's precomputed
        ``_block_keys``.  Every aligned length is probed — no break on
        a missing shorter key: the cap evicts oldest-first, and a
        prompt family's SHORTEST key is always its oldest, so stopping
        there would orphan the family's surviving longer keys (dead
        entries filling the cap while affinity decays to
        least-loaded)."""
        best = 0
        for ln, h in keys:
            reps = self._index.get((ln, h))
            if reps is not None and replica in reps:
                best = ln
        return best

    # ---- dispatch -------------------------------------------------------

    def _load(self, i: int) -> int:
        r = self.replicas[i]
        return r.n_queued + r.n_active + getattr(r, "n_staged", 0)

    def _candidates(self, cls: SLOClass) -> list[int]:
        """Replicas this class may dispatch to, most-preferred subset
        first: prefill-pool members (minus DOWN replicas — a killed or
        stalled replica takes no new work until re-join), narrowed by
        the class target when the fleet has both admission paths,
        minus replicas at the class's max_queue depth."""
        pool = [i for i, on in enumerate(self._prefill_role)
                if on and not self._down[i]]
        if cls.target == "ttft":
            pref = [i for i in pool
                    if self._scfg(self.replicas[i]).chunk_prefill > 0]
        else:
            pref = [i for i in pool
                    if self._scfg(self.replicas[i]).chunk_prefill == 0]
        cands = pref or pool
        if cls.max_queue > 0:
            cands = [i for i in cands
                     if self._depth.get((i, cls.name), 0) < cls.max_queue]
        return cands

    def _route(self, pend: _Pending) -> Optional[int]:
        """Pick a replica for one request (None: held by backpressure).
        Affinity: the best of the live per-replica prefix match (full
        pages + sub-page boundary) and the planned fleet-index match;
        a positive best score wins, ties broken least-loaded then
        lowest index.  No match (or affinity off): least-loaded.
        Replicas WITHOUT ``prefix_share`` never score: landing on a
        "matched" replica saves nothing there (every prompt re-prefills
        in full), so counting the planned index would concentrate load
        for fictitious wins — a disagg fleet (which rejects
        ``prefix_share``) routes purely least-loaded."""
        cls = self._classes[pend.cls]
        cands = self._candidates(cls)
        if not cands:
            self._backpressure_holds += 1
            return None
        best, best_score = None, 0
        if self.rcfg.affinity:
            keys = self._block_keys(pend.req.prompt)  # once, all cands
            for i in cands:
                if not self._scfg(self.replicas[i]).prefix_share:
                    continue
                score = max(
                    self.replicas[i].prefix_match_tokens(pend.req.prompt),
                    self._planned_match(keys, i),
                )
                if score > best_score or (
                    score == best_score and score > 0 and best is not None
                    and (self._load(i), i) < (self._load(best), best)
                ):
                    best, best_score = i, score
        if best is not None and best_score > 0:
            self._affinity_hits += 1
            self._affinity_tokens += best_score
            return best
        return min(cands, key=lambda i: (self._load(i), i))

    def _dispatch(self) -> None:
        """Drain the router queue into replicas, TTFT classes first
        (FIFO within a class); requests held by backpressure stay
        queued for the next tick.  Each pending leaves the queue only
        AFTER its replica submit succeeds: a raise mid-loop must not
        leave an already-dispatched request queued in two places (the
        forever-wedge a rebuild-after-the-loop would create)."""
        order = sorted(
            self._queue,
            key=lambda p: 0 if self._classes[p.cls].target == "ttft" else 1,
        )
        for pend in order:
            i = self._route(pend)
            if i is None:
                continue  # held by backpressure: stays queued
            # t0 back-dates the engine's TTFT clock to the ROUTER
            # submit: queue-held wall is part of what the tenant waited
            self.replicas[i].submit(pend.req, t0=pend.t0)
            if self.tracer.enabled:
                self.tracer.mark(pend.req.rid, "dispatch",
                                 time.perf_counter(), replica=i)
            self._queue.remove(pend)
            self._replica_of[pend.req.rid] = i
            self._inflight.add(pend.req.rid)
            self._pending_of[pend.req.rid] = pend
            self._depth[(i, pend.cls)] = (
                self._depth.get((i, pend.cls), 0) + 1
            )
            self._dispatched[i] += 1
            if self._scfg(self.replicas[i]).prefix_share:
                self._register(self._block_keys(pend.req.prompt), i)

    def _quarantine_poison(self, rep, exc: Exception) -> None:
        """A replica tick raised under the ``retry_budget == 0``
        raise-through contract.  When the raise came from an ADMISSION
        (the engine stamped ``_poison_rid`` before re-raising), the
        engine recovered its cache and requeued the failing request —
        BEHIND every in-flight request ``_recover_cache`` requeued for
        replay, so the queue head does NOT name it — leaving the caller
        to decide.  Fleet-side the router IS the caller, and one poison
        request must not stall the whole drain: pull the stamped rid
        out of the replica queue, quarantine it on the replica
        (reported, never requeued; the depth-release sweep in
        :meth:`step` then frees its ``max_queue`` slot), and keep
        ticking — the requeued in-flight requests replay bit-identically
        on later ticks.  A raise with NO admission stamp (a decode-step
        or staging failure — not attributable to one request) is not a
        poison admission: re-raise, the pre-router contract."""
        rid = rep.take_poison_rid()
        if rid is None or rid not in self._inflight:
            raise exc
        rep.drop_queued(rid)
        rep.quarantine(rid, f"{type(exc).__name__}: {exc}")

    # ---- SLO-aware load shedding (ISSUE 18) -----------------------------

    def _age(self, pend: _Pending) -> float:
        """Queue-wait age on the configured shed clock: logical
        (ticks held × tick_s — deterministic, trace-pure) when
        ``RouterConfig.tick_s`` is set, else wall."""
        if self.rcfg.tick_s > 0:
            return (self._tick - pend.tick) * self.rcfg.tick_s
        return time.perf_counter() - pend.t0

    def _do_shed(self, pend: _Pending, reason: str) -> None:
        """Give ``pend`` its explicit RequestShed outcome: out of the
        queue, out of the seen-set (the rid may be re-submitted — a
        retry replays the same (rid, prompt) stream bit-identically),
        counted against its class."""
        self._queue.remove(pend)
        rid = pend.req.rid
        self._seen.discard(rid)
        self._class_of.pop(rid, None)
        self._shed += 1
        self._shed_ptok += len(pend.req.prompt)
        self._class_shed[pend.cls] += 1
        self._class_shed_tok[pend.cls] += len(pend.req.prompt)
        self._open_by_class[pend.cls] -= 1
        self._shed_log.append(RequestShed(
            rid=rid, cls=pend.cls, reason=reason,
            waited_s=self._age(pend),
        ))
        self.tracer.shed(rid, time.perf_counter(), reason)

    def _displacement_victim(self, pend: _Pending,
                             shed_rids: set) -> Optional[_Pending]:
        """The queued pending a deadline-blown ``pend`` displaces:
        longest-waiting member of the LOWEST-priority class strictly
        below ``pend``'s (queue order is submission order, so the
        first hit per class is its longest-waiting), or None when no
        strictly-lower class has queued work."""
        my = self._prio[pend.cls]
        best, best_prio = None, my
        for p in self._queue:
            if p.req.rid in shed_rids or p is pend:
                continue
            pr = self._prio[p.cls]
            if pr > best_prio:
                best, best_prio = p, pr
        return best

    def _lowest_queued_victim(self, shed_rids: set) -> Optional[_Pending]:
        """Longest-waiting queued pending of the lowest-priority class
        with queued work — the ``max_open`` pressure valve's victim."""
        best, best_prio = None, -1
        for p in self._queue:
            if p.req.rid in shed_rids:
                continue
            pr = self._prio[p.cls]
            if pr > best_prio:
                best, best_prio = p, pr
        return best

    def _shed_tick(self) -> None:
        """The overload-control pass, start of every fleet tick.
        (1) deadline pass: a queued request older than its class's
        ``shed_after_s`` sheds a strictly-lower-priority queued victim
        if one exists (``"displaced"``) — top-class work never sheds
        while a lower class has work to give up — else itself
        (``"deadline"``).  (2) pressure valve: each class over its
        ``max_open`` sheds up to the excess from the lowest-priority
        queued work (``"over_open"``), bounded per tick.  Only queued
        work sheds — dispatched work always completes."""
        if not self._shed_enabled:
            return
        shed_rids: set[int] = set()
        for pend in list(self._queue):
            if pend.req.rid in shed_rids:
                continue
            c = self._classes[pend.cls]
            if c.shed_after_s <= 0 or self._age(pend) <= c.shed_after_s:
                continue
            victim = self._displacement_victim(pend, shed_rids)
            if victim is None:
                victim = pend
            self._do_shed(victim, "displaced" if victim is not pend
                          else "deadline")
            shed_rids.add(victim.req.rid)
        for c in self.rcfg.classes:
            if c.max_open <= 0:
                continue
            over = self._open_by_class[c.name] - c.max_open
            for _ in range(over):
                victim = self._lowest_queued_victim(shed_rids)
                if victim is None:
                    break  # nothing queued to give up: in-flight drains
                self._do_shed(victim, "over_open")
                shed_rids.add(victim.req.rid)

    # ---- autoscaling (disagg fleets) ------------------------------------

    def _autoscale(self) -> None:
        """Re-role one replica per decision from the staged-handoff
        backlog, hysteresis-bounded (see :class:`RouterConfig`)."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return
        n_pre = self.n_prefill_pool
        backlog = sum(r.n_staged for r in self.replicas)
        per = backlog / n_pre
        if per > self.rcfg.scale_down_backlog and n_pre > 1:
            # decode-bound: the deepest-staged prefill replica stops
            # taking new work and drains
            victim = max(
                (i for i, on in enumerate(self._prefill_role) if on),
                key=lambda i: (self.replicas[i].n_staged, -i),
            )
            self._prefill_role[victim] = False
            self._reroles += 1
            self._cooldown = self.rcfg.cooldown_ticks
        elif per < self.rcfg.scale_up_backlog and n_pre < len(self.replicas):
            # prefill-bound (or drained): the emptiest decode-pool
            # replica rejoins the prefill pool
            back = min(
                (i for i, on in enumerate(self._prefill_role) if not on),
                key=lambda i: (self._load(i), i),
            )
            self._prefill_role[back] = True
            self._reroles += 1
            self._cooldown = self.rcfg.cooldown_ticks

    # ---- replica chaos (ISSUE 17) ---------------------------------------

    def _chaos_tick(self) -> None:
        """Query the plan's ``serve/replica`` site once per live
        replica at this fleet tick (``index=tick``, ``key=replica`` —
        the explicit index keeps the schedule a pure function of the
        plan, so a chaos-vs-clean pair fires at the same ticks)."""
        t, self._tick = self._tick, self._tick + 1
        if self._chaos is None:
            return
        for i in range(len(self.replicas)):
            if self._down[i]:
                continue  # already out: an outage can't compound
            f = self._chaos.should_fire("serve/replica", index=t, key=i)
            if f is None:
                continue
            down = (f.down_ticks if f.down_ticks is not None
                    else self.rcfg.rejoin_ticks)
            if f.kind == "kill":
                self._kill_replica(i, down)
            elif f.kind == "stall":
                # frozen, not dead: state survives, requests just wait
                # (their TTFT eats the outage — the SLO report sees it)
                self._stalls += 1
                self._down[i] = max(1, down)

    def _kill_replica(self, i: int, down: int) -> None:
        """Kill replica ``i`` mid-stream: evacuate the dead engine and
        RE-ADMIT everything it owed at the head of the fleet queue (in
        rid order, original submit stamps kept — the outage is in the
        reported TTFT), through the same pending/queue machinery the
        PR-14 quarantine path uses.  The replica re-joins EMPTY after
        ``down`` ticks; rids key the PRNG streams, so the victims
        replay bit-identically wherever they land next."""
        rep = self.replicas[i]
        owed = rep.evacuate()
        self._kills += 1
        self._down[i] = max(1, down)
        victims: list[_Pending] = []
        for rid, un_ptok, n_gen in owed:
            self._inflight.discard(rid)
            self._replica_of.pop(rid, None)
            cls = self._class_of.get(rid)
            if cls is not None:
                self._depth[(i, cls)] = max(
                    0, self._depth.get((i, cls), 0) - 1
                )
            pend = self._pending_of.pop(rid, None)
            if pend is None:
                # a rid the router never routed (predispatched behind
                # its back): nothing to re-admit from — the one way a
                # request can be DROPPED, surfaced as a counter the
                # zero-loss law asserts on
                self._dropped += 1
                continue
            self._readmitted += 1
            leg = len(pend.req.prompt) - un_ptok
            self._readmitted_tokens += leg
            self._lost_tokens += n_gen
            if cls is not None:
                self._class_readmitted[cls] += 1
                self._class_readm_tok[cls] += leg
                self._class_lost[cls] += n_gen
            victims.append(pend)
        victims.sort(key=lambda p: p.req.rid)
        for pend in reversed(victims):
            self._queue.appendleft(pend)

    # ---- the tick -------------------------------------------------------

    def step(self) -> list[tuple[int, tuple[int, ...]]]:
        """One fleet tick: autoscale roles, dispatch what routes, fire
        due replica chaos, tick every LIVE replica (a down one burns
        an outage tick instead), collect finishes (with per-class
        TTFT).  Chaos fires AFTER dispatch: a kill at tick t takes out
        the replica WITH the work tick t just routed to it — the
        mid-stream case the re-admission machinery exists for (a
        before-dispatch kill would mostly find replicas drained by the
        previous tick's finishes).  Shedding runs FIRST: a request that
        blew its deadline must not consume a dispatch slot this tick,
        and the request-count law submitted == finished + shed + open
        holds at every return from this method."""
        self._shed_tick()
        if self.rcfg.autoscale:
            self._autoscale()
        self._dispatch()
        self._chaos_tick()
        finished: list[tuple[int, tuple[int, ...]]] = []
        for i, rep in enumerate(self.replicas):
            if self._down[i]:
                self._down[i] -= 1  # the outage elapses in fleet ticks
                continue
            try:
                done = rep.step()
            except Exception as exc:
                self._quarantine_poison(rep, exc)
                continue
            for rid, toks in done:
                self._inflight.discard(rid)
                self._pending_of.pop(rid, None)
                cls = self._class_of.get(rid)
                if cls is not None:
                    self._depth[(i, cls)] = max(
                        0, self._depth.get((i, cls), 0) - 1
                    )
                    self._class_tokens[cls] += len(toks)
                    self._class_done[cls] += 1
                    self._finished += 1
                    self._open_by_class[cls] -= 1
                    ttft = rep.take_ttft(rid)
                    if ttft is not None:
                        self._ttft[cls].observe(ttft)
                finished.append((rid, toks))
        # a QUARANTINED request never reaches the finish list — release
        # its backpressure depth here, or one poison request would pin
        # its class's max_queue slot forever (the engine-side livelock
        # lesson, router-level).  It is TERMINAL for the request-count
        # law: the router is done with it, so it leaves the open set as
        # finished (the law has no fourth outcome).
        for rid in [r for r in self._inflight
                    if self.replicas[self._replica_of[r]]
                    .is_quarantined(r)]:
            self._inflight.discard(rid)
            self._pending_of.pop(rid, None)
            i, cls = self._replica_of[rid], self._class_of.get(rid)
            if cls is not None:
                self._depth[(i, cls)] = max(
                    0, self._depth.get((i, cls), 0) - 1
                )
                self._finished += 1
                self._open_by_class[cls] -= 1
        if self.tracer.enabled:
            self.tracer.collect()
        return finished

    @property
    def busy(self) -> bool:
        """Anything still owed: router-queued, replica-queued/active/
        staged, or finishes parked by a raise-through — the drain
        condition ``run`` and the traffic harness share."""
        return bool(self._queue) or any(
            r.n_queued or r.n_active or getattr(r, "n_staged", 0)
            or r.has_buffered_finishes
            for r in self.replicas
        )

    def _begin_drain(self) -> dict:
        """Open a drain window: snapshot every lifetime counter the
        report deltas against, and reset the per-class TTFT reservoirs
        (this window's tails).  ``run`` and ``bench.traffic``'s
        open-loop harness are the two drivers — ONE accounting
        definition between them."""
        self._reset_ttft()
        return dict(
            ptok=[self._prefill_of(r) for r in self.replicas],
            stok=[self._shared_of(r) for r in self.replicas],
            sub=[self._subpage_of(r) for r in self.replicas],
            disp_decode=[r.dispatches for r in self.replicas],
            hs=[r.host_syncs for r in self.replicas],
            # the window's "submitted" leg: prompts still PENDING
            # admission anywhere — the router queue plus every
            # replica's own queue (a prior step() may have dispatched
            # without draining; those prompts prefill during THIS
            # window, so the counter law needs them).  Disagg
            # handed-off requests sit in the INNER engine's queue
            # already prefilled, so rep._queue (the front queue) is
            # exactly the not-yet-prefilled set.
            subm=self._submitted_ptok - sum(
                len(p.req.prompt) for p in self._queue
            ) - sum(len(q.prompt)
                    for r in self.replicas for q in r._queue),
            hits=self._affinity_hits, atok=self._affinity_tokens,
            holds=self._backpressure_holds, rer=self._reroles,
            kills=self._kills, stalls=self._stalls,
            readm=self._readmitted, readm_tok=self._readmitted_tokens,
            lost=self._lost_tokens, dropped=self._dropped,
            shed=self._shed, shed_ptok=self._shed_ptok,
            cshed=dict(self._class_shed),
            cshed_tok=dict(self._class_shed_tok),
            disp=list(self._dispatched),
            ctok=dict(self._class_tokens),
            cdone=dict(self._class_done),
            cptok=dict(self._class_ptok),
            creadm=dict(self._class_readmitted),
            creadm_tok=dict(self._class_readm_tok),
            clost=dict(self._class_lost),
        )

    def _drain_report(self, snap: dict, wall: float,
                      outputs: Optional[dict] = None,
                      completed: Optional[int] = None,
                      tokens: Optional[int] = None) -> RouterReport:
        """Close a drain window opened by :meth:`_begin_drain`.  The
        traffic harness passes ``completed``/``tokens`` instead of an
        outputs map (a 500k-drain report must not hold 500k token
        tuples — it folds a digest instead)."""
        if outputs is not None:
            completed = len(outputs)
            tokens = sum(len(t) for t in outputs.values())
        classes = []
        for c in self.rcfg.classes:
            res = self._ttft[c.name]
            ctoks = self._class_tokens[c.name] - snap["ctok"][c.name]
            cptok = self._class_ptok[c.name] - snap["cptok"][c.name]
            readm_tok = (self._class_readm_tok[c.name]
                         - snap["creadm_tok"][c.name])
            lost = self._class_lost[c.name] - snap["clost"][c.name]
            shed_tok = (self._class_shed_tok[c.name]
                        - snap["cshed_tok"][c.name])
            # shed prompts are waste the tenant asked for and never
            # got: out of the useful leg (max() guards the window
            # where the shed leg was submitted before the snapshot),
            # INTO the denominator — shed waste charges the shedding
            # class, the MegaScale accounting extended to overload
            useful = ctoks + max(0, cptok - shed_tok)
            waste = readm_tok + lost + shed_tok
            classes.append(ClassReport(
                name=c.name,
                completed=self._class_done[c.name]
                - snap["cdone"][c.name],
                tokens=ctoks,
                ttft_p50_s=_percentile(res.sample, 50),
                ttft_p99_s=_percentile(res.sample, 99),
                tokens_per_s=ctoks / wall if wall else 0.0,
                ttft_exact=res.exact,
                readmitted=self._class_readmitted[c.name]
                - snap["creadm"][c.name],
                goodput_frac=(useful / (useful + waste)
                              if useful + waste else 1.0),
                shed=self._class_shed[c.name] - snap["cshed"][c.name],
                shed_tokens=shed_tok,
            ))
        return RouterReport(
            completed=completed or 0,
            tokens_generated=tokens or 0,
            wall_s=wall,
            tokens_per_s=(tokens or 0) / wall if wall else 0.0,
            outputs=(tuple(sorted(outputs.items()))
                     if outputs is not None else ()),
            classes=tuple(classes),
            prefill_tokens=sum(
                self._prefill_of(r) - p0
                for r, p0 in zip(self.replicas, snap["ptok"])
            ),
            shared_tokens=sum(
                self._shared_of(r) - s0
                for r, s0 in zip(self.replicas, snap["stok"])
            ),
            # shed prompts never prefill: excluded from the window's
            # submitted leg (as a DELTA — a pre-window shed stays out),
            # so prefill + shared == submitted + readmitted stays exact
            # under shedding
            submitted_prompt_tokens=(self._submitted_ptok - snap["subm"])
            - (self._shed_ptok - snap["shed_ptok"]),
            subpage_tokens=sum(
                self._subpage_of(r) - s0
                for r, s0 in zip(self.replicas, snap["sub"])
            ),
            affinity_hits=self._affinity_hits - snap["hits"],
            affinity_tokens=self._affinity_tokens - snap["atok"],
            backpressure_holds=self._backpressure_holds - snap["holds"],
            reroles=self._reroles - snap["rer"],
            dispatched=tuple(
                d - d0 for d, d0 in zip(self._dispatched, snap["disp"])
            ),
            dispatches=sum(
                r.dispatches - d0
                for r, d0 in zip(self.replicas, snap["disp_decode"])
            ),
            host_syncs=sum(
                r.host_syncs - h0
                for r, h0 in zip(self.replicas, snap["hs"])
            ),
            kills=self._kills - snap["kills"],
            stalls=self._stalls - snap["stalls"],
            readmitted=self._readmitted - snap["readm"],
            readmitted_tokens=self._readmitted_tokens
            - snap["readm_tok"],
            lost_tokens=self._lost_tokens - snap["lost"],
            dropped=self._dropped - snap["dropped"],
            shed=self._shed - snap["shed"],
            shed_tokens=self._shed_ptok - snap["shed_ptok"],
        )

    def run(self, requests: Sequence = (),
            max_steps: int = 100_000) -> RouterReport:
        """Submit ``requests`` — ``Request``s (default class) or
        ``(tenant, Request)`` pairs — and drain the whole fleet.
        Counters in the report are THIS drain's deltas, so a reused
        router's reports stay internally consistent."""
        for r in requests:
            if isinstance(r, Request):
                self.submit(r)
            else:
                tenant, req = r
                self.submit(req, tenant=tenant)
        snap = self._begin_drain()
        outputs: dict[int, tuple[int, ...]] = {}
        steps = 0
        t0 = time.perf_counter()
        while self.busy:
            if steps >= max_steps:
                raise RuntimeError(
                    f"fleet did not drain in {max_steps} steps "
                    f"({self.n_queued} queued, {self.n_active} active)"
                )
            for rid, toks in self.step():
                outputs[rid] = toks
            steps += 1
        wall = time.perf_counter() - t0
        return self._drain_report(snap, wall, outputs=outputs)

    # ---- fleet counter taps ---------------------------------------------

    @staticmethod
    def _prefill_of(r) -> int:
        """Prompt tokens COMPUTED on this replica: the engine's prefill
        programs plus, for disagg, the staging slice's."""
        if isinstance(r, DisaggEngine):
            return r.engine.prefill_tokens + r.stage_prefill_tokens
        return r.prefill_tokens

    @staticmethod
    def _shared_of(r) -> int:
        if isinstance(r, DisaggEngine):
            return r.engine.shared_tokens
        return r.shared_tokens

    @staticmethod
    def _subpage_of(r) -> int:
        if isinstance(r, DisaggEngine):
            return r.engine.subpage_tokens
        return r.subpage_tokens
