"""tpuscratch.serve — sharded autoregressive inference.

The serving layer over the training stack: a block-paged KV cache
sharded on the SAME (dp, sp) mesh the train step uses (kvcache, with
per-page refcounts + a prefix trie for cross-request sharing, and a
host paging tier — HostPageStore/TieredPageAllocator — spilling cold
pages to pinned host memory behind ``ServeConfig(kv_host_pages)``), a
cached single-token decode step numerically equivalent to the full
forward (decode + ops.attention.decode_attention) with an optional
device-resident macro-step loop fusing T whole engine ticks into one
compiled ``lax.scan`` (``ServeConfig(macro_steps)``: one dispatch and
one host sync per T tokens, greedy output bit-identical at any T),
deterministic per-request sampling (sampling), a continuous-batching
engine with free-page-watermark admission and zero steady-state
recompiles (engine; opt-in prefix sharing — full-page trie plus
sub-page boundary continuations — chunked prefill, and wave-scheduled
spill/prefetch with cold hits measured), a prefill/decode-
disaggregated front end shipping finished KV pages between mesh
slices through comm/p2p (disagg), and a fleet router dispatching
across N engine replicas with prefix-affine load balancing,
per-tenant SLO classes, and an autoscaled prefill:decode pool
(router) — greedy output bit-identical under any routing.
"""

from tpuscratch.serve.decode import (  # noqa: F401
    CompileCounter,
    build_context_prefill,
    build_decode_loop,
    build_decode_step,
    build_prefill,
    build_verify_step,
    propose_draft,
)
from tpuscratch.serve.disagg import (  # noqa: F401
    DisaggEngine,
    DisaggReport,
    build_migrate,
)
from tpuscratch.serve.engine import (  # noqa: F401
    GenerateReport,
    Request,
    ServeConfig,
    ServeEngine,
    init_embed,
)
from tpuscratch.serve.kvcache import (  # noqa: F401
    CacheGeometry,
    HostPageStore,
    HostTierError,
    PageAllocator,
    PrefixCache,
    ResidencyPolicy,
    TieredPageAllocator,
    dequantize_pages,
    host_leaf_shapes,
    init_kv_cache,
    is_quantized_kv_dtype,
    kv_cache_spec,
    quantize_pages,
)
from tpuscratch.serve.router import (  # noqa: F401
    ClassReport,
    FleetRouter,
    RequestShed,
    RouterConfig,
    RouterReport,
    SLOClass,
)
from tpuscratch.serve.sampling import (  # noqa: F401
    accept_speculative,
    request_key,
    sample_batch,
    sample_logits,
    target_probs,
)
