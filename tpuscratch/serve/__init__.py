"""tpuscratch.serve — sharded autoregressive inference.

The serving layer over the training stack: a block-paged KV cache
sharded on the SAME (dp, sp) mesh the train step uses (kvcache), a
cached single-token decode step numerically equivalent to the full
forward (decode + ops.attention.decode_attention), deterministic
per-request sampling (sampling), and a continuous-batching engine with
free-page-watermark admission and zero steady-state recompiles (engine).
"""

from tpuscratch.serve.decode import (  # noqa: F401
    CompileCounter,
    build_decode_step,
    build_prefill,
    build_verify_step,
    propose_draft,
)
from tpuscratch.serve.engine import (  # noqa: F401
    GenerateReport,
    Request,
    ServeConfig,
    ServeEngine,
    init_embed,
)
from tpuscratch.serve.kvcache import (  # noqa: F401
    CacheGeometry,
    PageAllocator,
    dequantize_pages,
    init_kv_cache,
    kv_cache_spec,
    quantize_pages,
)
from tpuscratch.serve.sampling import (  # noqa: F401
    accept_speculative,
    request_key,
    sample_batch,
    sample_logits,
    target_probs,
)
