"""Prefill/decode disaggregation: staged prefill, KV-page migration
over ``comm/p2p``, and a handoff queue in front of the decode engine.

The DistServe (OSDI '24) split on this framework's mesh: prefill and
decode have opposite resource shapes — prefill is compute-bound and
bursty, decode is bandwidth-bound and latency-sensitive — so a
monolithic engine lets every long admission perturb every resident
stream.  This module separates them into two POOLS on the same mesh:

- **prefill slice**: prompts prefill into a STAGING page pool whose
  writes land on one designated dp group (``prefill_group``) — the
  mpi9.cpp sub-communicator idea (a rank subset owning one phase of the
  computation) expressed as the dp-group ownership the paged cache
  already has (``build_prefill``'s owner-local drop-mode writes);
- **handoff**: finished prompt pages (and, for quantized pools, their scale
  planes) ship from the staging pool into the decode engine's pool
  through ONE compiled migration program per destination group — a
  ``lax.ppermute`` pair transfer over the dp axis
  (``comm.p2p.send_tree``), the reference's nonblocking neighbor
  exchange (mpi5.cpp Isend/Irecv/Waitall) applied to cache migration;
- **decode slice**: the unchanged :class:`~tpuscratch.serve.engine.
  ServeEngine` decodes migrated requests via ``admit_prefilled`` —
  its own prefill programs never run for a handed-off request.

Migration is EXACT (ppermute moves bytes, the staged pages hold the
same projections monolithic prefill writes, and the first token was
sampled from the same ``request_key(seed, rid, 0)`` draw), so greedy
output is bit-identical to the monolithic engine — test-gated on 1x1
and 2x2 CPU meshes (on 1x1 the permutation is the self-pair
``[(0, 0)]``: the handoff machinery runs unchanged, the wire is loop-
back).  A mid-handoff failure (a :class:`~tpuscratch.runtime.errors.
CommError`, chaos site ``serve/handoff``) is retried through
``ft.retry``; a handoff that exhausts its retry budget DEGRADES: the
staged pages are dropped and the request re-enters the decode engine's
own queue for a LOCAL monolithic prefill — graceful degradation to the
single-engine path, with byte-identical output (the PR 3 replay
contract).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.comm.p2p import send_tree
from tpuscratch.ft.retry import RetryPolicy, retry
from tpuscratch.serve.decode import build_prefill
from tpuscratch.serve.engine import (
    GenerateReport,
    Request,
    ServeConfig,
    ServeEngine,
    _bucket,
    validate_request,
)
from tpuscratch.models.transformer import TransformerConfig
from tpuscratch.serve.kvcache import (
    CacheGeometry,
    PageAllocator,
    init_kv_cache,
    kv_cache_spec,
)
from tpuscratch.serve.sampling import request_key

#: the handoff retry contract: absorb transient migration faults fast,
#: then degrade to the local monolithic path within ~a tenth of a second
DEFAULT_HANDOFF_RETRY = RetryPolicy(max_attempts=3, base_s=0.01, max_s=0.1)


def build_migrate(mesh: Mesh, stage_geom: CacheGeometry,
                  src_group: int, dst_group: int,
                  dp: str = "dp", sp: str = "sp",
                  quantized: bool = False):
    """Compiled KV-page migration over ``mesh``: jit'd
    fn(serve_kv, stage_kv, src_rows, dst_rows) -> serve_kv', with the
    serve pool donated (pages land in place).

    ``src_rows``/``dst_rows`` are ``(dp_size, n_rows)`` int32 page-id
    tables in the engine's owner-row idiom: real LOCAL ids on the
    participating group's row, the pool-size sentinel everywhere else
    (and on padding entries past the request's true page count).  The
    body gathers the staged page payloads — every cache leaf, so the
    quantized rungs' scale planes (int8 and fp8 alike) ride the same
    transfer — ships them ``src_group ->
    dst_group`` with ONE static ppermute pair per leaf
    (``comm.p2p.send_tree``), and scatters them into the destination
    group's serve pool with drop-mode writes (sentinel rows vanish,
    exactly like prefill's owner-local page writes).

    The row width is static (the engine passes its page-footprint
    ceiling ``max_pages``), so there is ONE migration program per
    destination group — migration can never recompile in steady state,
    at the cost of shipping the footprint ceiling rather than the exact
    page count (the ledger test pins that payload analytically)."""
    if not 0 <= src_group < mesh.shape[dp]:
        raise ValueError(f"src_group {src_group} not in mesh dp axis")
    if not 0 <= dst_group < mesh.shape[dp]:
        raise ValueError(f"dst_group {dst_group} not in mesh dp axis")
    pair = [(src_group, dst_group)]

    def body(serve_kv, stage_kv, src_rows, dst_rows):
        src = jnp.clip(src_rows[0], 0, stage_geom.n_pages - 1)
        dst = dst_rows[0]
        payload = {
            name: leaf[:, src] for name, leaf in stage_kv.items()
        }
        shipped = send_tree(payload, dp, pair)
        return {
            name: serve_kv[name].at[:, dst].set(shipped[name], mode="drop")
            for name in serve_kv
        }

    kspec = kv_cache_spec(dp, sp, quantized)
    return run_spmd(
        mesh,
        body,
        (kspec, kspec, P(dp), P(dp)),
        kspec,
        donate_argnums=(0,),
    )


@dataclasses.dataclass
class _Staged:
    """One prefilled request waiting in the handoff queue."""

    req: Request
    pages: list[int]        # staging-pool ids (prefill group local)
    first_token: int        # sampled at prefill (stream position 0)


@dataclasses.dataclass(frozen=True)
class DisaggReport:
    """A disaggregated drain: the decode engine's report plus the
    prefill-slice and handoff accounting."""

    engine: GenerateReport          # decode-side (outputs live here)
    stage_prefills: int             # prompts prefilled on the slice
    stage_prefill_tokens: int
    handoffs: int                   # page migrations that landed
    migrated_pages: int             # real pages shipped (excl. padding)
    handoff_retries: int            # failed attempts that were retried
    degraded: int                   # handoffs that fell back to local
    handoff_wire_bytes: float       # static per-migration payload x handoffs

    @property
    def outputs(self):
        return self.engine.outputs

    @property
    def completed(self) -> int:
        return self.engine.completed

    @property
    def tokens_generated(self) -> int:
        return self.engine.tokens_generated


class DisaggEngine:
    """Prefill/decode-disaggregated serving over one mesh.

    Wraps an UNCHANGED :class:`ServeEngine` (the decode slice) with a
    staging prefill pool owned by dp group ``prefill_group`` and a
    handoff queue.  ``submit`` queues requests; each ``step`` (1)
    prefills queued prompts into the staging pool, (2) migrates
    finished prompt pages into decode groups that have a free slot +
    pages, (3) runs one decode tick.  ``run`` drains.

    The decode engine's admission machinery is bypassed for handed-off
    requests (``admit_prefilled``) but fully alive: a handoff that
    exhausts its migration retries degrades into ``engine.submit`` — a
    local monolithic prefill — so disaggregation can only ever ADD a
    path, never lose a request.

    ``stage_pages`` sizes the staging pool (default: the serve pool's
    ``n_pages``); it bounds how far prefill can run ahead of decode —
    the disaggregation headroom knob."""

    def __init__(self, mesh: Mesh, cfg: TransformerConfig,
                 scfg: ServeConfig, params: Optional[dict] = None,
                 embed=None, dp: str = "dp", sp: str = "sp",
                 sink=None, chaos=None, recorder=None,
                 prefill_group: int = 0,
                 stage_pages: Optional[int] = None,
                 handoff_retry: RetryPolicy = DEFAULT_HANDOFF_RETRY,
                 tracer=None):
        if scfg.prefix_share or scfg.chunk_prefill:
            raise ValueError(
                "DisaggEngine stages MONOLITHIC prefills; run prefix "
                "sharing / chunked prefill on the ServeEngine directly"
            )
        self.engine = ServeEngine(
            mesh, cfg, scfg, params=params, embed=embed, dp=dp, sp=sp,
            sink=sink, chaos=chaos, recorder=recorder, tracer=tracer,
        )
        self.mesh, self.cfg, self.scfg = mesh, cfg, scfg
        self._dp, self._sp = dp, sp
        self._dp_size = mesh.shape[dp]
        if not 0 <= prefill_group < self._dp_size:
            raise ValueError(
                f"prefill_group {prefill_group} not in [0, {self._dp_size})"
            )
        self.prefill_group = prefill_group
        self._quantized = self.engine._quantized
        self.stage_geom = CacheGeometry(
            cfg.n_layers, stage_pages or scfg.n_pages, scfg.page_size,
            cfg.n_heads, cfg.d_head,
        )
        self._stage_kv = self._fresh_stage_kv()
        self._stage_alloc = PageAllocator(self.stage_geom.n_pages)
        self._stage_prefills: dict[int, object] = {}  # bucket -> program
        self._migrates: dict[int, object] = {}        # dst group -> program
        self._queue: collections.deque[Request] = collections.deque()
        self._handoff: collections.deque[_Staged] = collections.deque()
        self._seen: set[int] = set()
        # finishes collected by an in-progress tick (the engine's
        # _finish_buf contract, front-end half — see step())
        self._finish_buf: list[tuple[int, tuple[int, ...]]] = []
        self._chaos = chaos
        self._retry = handoff_retry
        self._stage_count = 0
        self._stage_tokens = 0
        self._handoffs = 0
        self._migrated_pages = 0
        self._retried = 0
        self._degraded = 0
        self._stage_s = 0.0

    # ---- introspection --------------------------------------------------

    @property
    def n_staged(self) -> int:
        """Requests prefilled and waiting in the handoff queue."""
        return len(self._handoff)

    @property
    def stage_prefill_tokens(self) -> int:
        """Engine-lifetime prompt tokens prefilled on the staging slice
        — the disagg half of the fleet prefill-counter law (the router
        sums this with the decode engine's ``prefill_tokens``)."""
        return self._stage_tokens

    def prefix_match_tokens(self, prompt) -> int:
        """Router affinity probe (``ServeEngine`` contract): delegates
        to the decode-side engine's prefix index, which is empty —
        disagg runs without ``prefix_share`` (staged prefills are
        monolithic) — so this returns 0 and the router falls back to
        least-loaded for disagg fleets."""
        return self.engine.prefix_match_tokens(prompt)

    def take_ttft(self, rid: int):
        """Pop one finished request's TTFT (stamped when its staged
        prefill sampled the first token)."""
        return self.engine.take_ttft(rid)

    # decode-side dispatch accounting (ISSUE 15), delegated to the
    # decode engine where the macro loop runs; the staging slice's
    # prefill dispatches are deliberately not counted (the contract is
    # decode-side, like ServeEngine's)
    @property
    def dispatches(self) -> int:
        return self.engine.dispatches

    @property
    def host_syncs(self) -> int:
        return self.engine.host_syncs

    @property
    def decode_rounds(self) -> int:
        return self.engine.decode_rounds

    @property
    def macro_steps_effective(self) -> int:
        return self.engine.macro_steps_effective

    def validate(self, req: Request) -> None:
        """The decode engine's rules plus the staging-pool bound —
        the front-door contract (``ServeEngine.validate``)."""
        validate_request(req, self.scfg)
        self.validate_local(req)

    def validate_local(self, req: Request) -> None:
        """The replica-specific half: the staging-pool bound (stricter
        than ``max_seq`` when ``stage_pages`` undercuts the prompt)."""
        if (self.stage_geom.pages_for(len(req.prompt))
                > self.stage_geom.n_pages):
            # would never fit the staging pool: refusing now beats the
            # silent forever-requeue a too-small pool would otherwise be
            raise ValueError(
                f"request {req.rid}: prompt needs "
                f"{self.stage_geom.pages_for(len(req.prompt))} staging "
                f"pages, pool holds {self.stage_geom.n_pages}"
            )

    # the fleet router's quarantine surface, delegated to the decode
    # engine (where the TTFT stamps and quarantine map live) — except
    # the queue walk, which must cover the front queue too
    @property
    def quarantined(self) -> dict:
        return self.engine.quarantined

    def quarantine(self, rid: int, reason: str, attempts: int = 1) -> None:
        self.engine.quarantine(rid, reason, attempts=attempts)

    def take_poison_rid(self):
        return self.engine.take_poison_rid()

    def is_quarantined(self, rid: int) -> bool:
        return self.engine.is_quarantined(rid)

    # the per-request tracer lives on the decode engine (one tracer per
    # replica, both halves) — the router's set_tracer contract
    @property
    def tracer(self):
        return self.engine.tracer

    def set_tracer(self, tracer) -> None:
        self.engine.set_tracer(tracer)

    @property
    def has_buffered_finishes(self) -> bool:
        return bool(self._finish_buf) or self.engine.has_buffered_finishes

    def drop_queued(self, rid: int) -> bool:
        for req in list(self._queue):
            if req.rid == rid:
                self._queue.remove(req)
                return True
        return self.engine.drop_queued(rid)

    def evacuate(self) -> list[tuple[int, int, int]]:
        """Kill this replica (fleet-scale chaos — the
        ``ServeEngine.evacuate`` contract, disagg front end included):
        tear down the front queue, the handoff queue, the staging pool
        and the wrapped decode engine, and return every owed
        ``(rid, unaccounted_prompt_tokens, lost_generated_tokens)``
        triple.  The staging pool's accounting mirrors the engine's:

        - a FRONT-QUEUED request never touched a prefill program — its
          whole prompt is unaccounted;
        - a STAGED request (in the handoff queue) was fully prefilled
          on the staging slice (``stage_prefill_tokens`` counted it,
          and that counter feeds the router's prefill leg), so its
          prompt is fully accounted — but the first token sampled at
          staging dies with the pool: 1 lost generated token;
        - a buffered finish is fully accounted prompt, fully lost
          output (the engine's own rule);
        - everything living INSIDE the decode engine (including
          degraded requests in its queue) comes from
          ``engine.evacuate()`` — no rid appears in both halves, by
          the step() hand-over discipline.

        The object survives as the re-join replica (compiled staging
        and migration programs are process state); ``_seen`` clears
        with the scheduling state — the router's fleet-level seen set
        guards rid uniqueness across the kill.  Lifetime counters
        (``stage_prefill_tokens``, handoffs) keep accumulating."""
        owed: list[tuple[int, int, int]] = []
        for req in self._queue:
            owed.append((req.rid, len(req.prompt), 0))
        for st in self._handoff:
            owed.append((st.req.rid, 0, 1))
        for rid, toks in self._finish_buf:
            owed.append((rid, 0, len(toks)))
        if self.engine.tracer.enabled and owed:
            # front-half victims (the decode engine marks its own in
            # engine.evacuate below; killed() is idempotent per attempt)
            now = time.perf_counter()
            for rid, _unaccounted, lost in owed:
                self.engine.tracer.killed(rid, now, lost_tokens=lost)
        self._queue.clear()
        self._handoff.clear()
        self._finish_buf = []
        self._stage_kv = self._fresh_stage_kv()
        self._stage_alloc = PageAllocator(self.stage_geom.n_pages)
        self._seen.clear()
        owed.extend(self.engine.evacuate())
        return owed

    @property
    def n_queued(self) -> int:
        return len(self._queue) + self.engine.n_queued

    @property
    def n_active(self) -> int:
        return self.engine.n_active

    def stage_free_pages(self) -> int:
        return self._stage_alloc.n_free

    @property
    def handoff_wire_bytes(self) -> float:
        """Static payload bytes ONE migration ships per device: the
        footprint-ceiling (``max_pages``) page payload of every cache
        leaf at the device-local head slice — exactly the
        collective-permute payload the obs ledger reads off the
        compiled migration program (test-pinned)."""
        M = self.scfg.max_pages
        sp_size = self.mesh.shape[self._sp]
        total = 0.0
        for leaf in self._stage_kv.values():
            # elements one page id drags across all layers, heads local
            per_page = (leaf.size // leaf.shape[1]) / sp_size
            total += per_page * leaf.dtype.itemsize * M
        return total

    # ---- request lifecycle ----------------------------------------------

    def submit(self, req: Request, t0: Optional[float] = None) -> None:
        """Validate and queue for the prefill slice (the decode engine's
        validation rules, applied before staging).  ``t0`` back-dates
        the TTFT clock (the ``ServeEngine.submit`` contract)."""
        self.validate(req)
        if req.rid in self._seen:
            raise ValueError(f"request id {req.rid} already used")
        self._seen.add(req.rid)
        # TTFT clock starts at the FRONT-END submit, not at the later
        # decode-side admission (the engine's stamp_submit setdefault
        # keeps this when the request re-enters a degraded handoff)
        self.engine.stamp_submit(req.rid, t0)
        self.engine.tracer.begin(req.rid, self.engine._submit_t[req.rid])
        self._queue.append(req)

    def _stage_prefill(self, req: Request) -> Optional[_Staged]:
        """Prefill ``req`` into the staging pool (prompt pages only —
        the generation budget is the decode side's reservation).  None
        when the staging pool cannot cover the prompt right now."""
        eng, geom = self.engine, self.stage_geom
        n_tok = len(req.prompt)
        pages = self._stage_alloc.alloc(geom.pages_for(n_tok))
        if pages is None:
            return None
        bucket = _bucket(n_tok)
        if bucket not in self._stage_prefills:
            self._stage_prefills[bucket] = build_prefill(
                self.mesh, self.cfg, geom, dp=self._dp, sp=self._sp,
                counter=eng.prefill_counter, quantized=self._quantized,
            )
        x = np.zeros((bucket, self.cfg.d_model), np.float32)
        x[:n_tok] = eng._embed_np[list(req.prompt)]
        page_rows = np.full(
            (self._dp_size, self.scfg.max_pages), geom.n_pages, np.int32
        )
        page_rows[self.prefill_group, : len(pages)] = pages
        try:
            with eng.timeline.span("serve/stage_prefill"):
                out, self._stage_kv = self._stage_prefills[bucket](
                    eng.params, self._stage_kv, jnp.asarray(x),
                    jnp.asarray(page_rows), jnp.int32(n_tok),
                )
                logits = eng._unembed(out[n_tok - 1][None], eng.embed)
                tok = int(eng._sample(
                    request_key(self.scfg.seed, req.rid, 0)[None], logits
                )[0])
        except Exception:
            # the staged pool was donated and may be consumed: reset it
            # and drop every staged-but-not-handed-off request back to
            # the queue for deterministic replay (the engine recovery
            # contract, staging-side).  ``req`` itself is still at the
            # queue head — the caller only pops on success
            if eng.tracer.enabled:
                eng._trace_span((req.rid,), "prefill", staged=True,
                                failed=True)
            self._recover_stage()
            self._stage_alloc = PageAllocator(geom.n_pages)
            raise
        self._stage_count += 1
        self._stage_tokens += n_tok
        self._stage_s += eng._last_span_s()
        if eng.tracer.enabled:
            eng._trace_span((req.rid,), "prefill", staged=True,
                            tokens=n_tok)
        eng._mark_first_token(req.rid)  # TTFT: first token exists HERE
        return _Staged(req=req, pages=pages, first_token=tok)

    def _fresh_stage_kv(self) -> dict:
        """A zeroed staging pool committed to the engine's canonical
        cache sharding (the engine's one-sharding-one-compile rule,
        staging-side)."""
        import jax

        return {
            name: jax.device_put(leaf, self.engine._kv_sharding[name])
            for name, leaf in init_kv_cache(
                self.stage_geom, self._dp_size, self.engine._kv_jnp_dtype
            ).items()
        }

    def _recover_stage(self) -> None:
        """Reset the staging pool and requeue staged requests (their
        pages no longer hold valid K/V)."""
        while self._handoff:
            st = self._handoff.pop()
            self._queue.appendleft(st.req)
        self._stage_kv = self._fresh_stage_kv()

    def _find_decode_slot(self, req: Request) -> Optional[tuple[int, int]]:
        """(slot, group) of a free decode slot whose group can cover the
        request's WHOLE footprint — the engine's admission watermark,
        applied at handoff time.  Under the tier the footprint spans
        both tiers: the migrated prompt pages must land on DEVICE (the
        compiled migration scatters into the device pool), the budget
        tail is a host-side reservation — a migrated page may end up in
        either tier."""
        eng = self.engine
        need = eng.geom.pages_for(len(req.prompt) + req.max_new)
        n_pp = eng.geom.pages_for(len(req.prompt))
        for s, slot in enumerate(eng._slots):
            if slot is None:
                g = eng._group_of(s)
                alloc = eng._allocators[g]
                if eng._tiered:
                    if alloc.can_alloc(need, resident=n_pp):
                        return s, g
                elif alloc.n_free >= need:
                    return s, g
        return None

    def _migrate_program(self, dst_group: int):
        if dst_group not in self._migrates:
            self._migrates[dst_group] = build_migrate(
                self.mesh, self.stage_geom, self.prefill_group, dst_group,
                dp=self._dp, sp=self._sp, quantized=self._quantized,
            )
        return self._migrates[dst_group]

    def _try_handoff(self, staged: _Staged) -> bool:
        """Migrate one staged request into the decode slice; False when
        no decode slot/pages are free yet (it stays queued).  Raises
        nothing for migration failures: retries absorb transients and
        the exhausted case degrades to a local monolithic prefill."""
        eng, req = self.engine, staged.req
        found = self._find_decode_slot(req)
        if found is None:
            return False
        slot, group = found
        need = eng.geom.pages_for(len(req.prompt) + req.max_new)
        n_pg = self.stage_geom.pages_for(len(req.prompt))
        if eng._tiered:
            # migrated prompt pages land DEVICE-resident (the compiled
            # scatter writes the device pool); the budget tail is a
            # host reservation that pages in when the frontier arrives
            dst_pages = eng._tier_op(
                group,
                lambda: eng._allocators[group].alloc(need, resident=n_pg),
            )
            if dst_pages is None:
                return False  # the gate raced a degrade; retry next tick
            eng._allocators[group].mark_written(dst_pages[:n_pg])
            eng._allocators[group].touch(dst_pages)
            dst_row = [eng._allocators[group].device_page(lp)
                       for lp in dst_pages[:n_pg]]
        else:
            dst_pages = eng._allocators[group].alloc(need)
            assert dst_pages is not None  # _find_decode_slot checked
            dst_row = dst_pages[:n_pg]
        src_rows = np.full(
            (self._dp_size, self.scfg.max_pages),
            self.stage_geom.n_pages, np.int32,
        )
        src_rows[self.prefill_group, :n_pg] = staged.pages
        dst_rows = np.full(
            (self._dp_size, self.scfg.max_pages),
            eng.geom.n_pages, np.int32,
        )
        dst_rows[group, :n_pg] = dst_row
        program = self._migrate_program(group)
        attempts = {"n": 0}

        def attempt() -> None:
            attempts["n"] += 1
            if self._chaos is not None:
                self._chaos.maybe_fail("serve/handoff", key=req.rid,
                                       op="comm/migrate")
            try:
                with eng.timeline.span("serve/handoff"):
                    eng._kv = program(
                        eng._kv, self._stage_kv,
                        jnp.asarray(src_rows), jnp.asarray(dst_rows),
                    )
            except Exception:
                # the donated decode pool may be consumed mid-program:
                # reset it (in-flight decode requests replay) so the
                # NEXT attempt migrates into a valid pool
                if eng.tracer.enabled:
                    eng._trace_span((req.rid,), "handoff", failed=True,
                                    try_n=attempts["n"])
                eng._recover_cache()
                raise
            if eng.tracer.enabled:
                eng._trace_span((req.rid,), "handoff",
                                try_n=attempts["n"])

        try:
            retry(attempt, self._retry, op="serve/handoff")
        except Exception as exc:
            # graceful degradation: drop the staged copy, hand the
            # request to the decode engine's own (monolithic) admission
            # — outputs stay byte-identical because rids key the
            # sampling streams and prefill is deterministic
            eng._allocators[group].free(dst_pages)
            self._stage_alloc.free(staged.pages)
            self._retried += attempts["n"] - 1
            self._degraded += 1
            eng.metrics.counter("serve/handoff_degraded").inc()
            eng.sink.emit(
                "ft/degrade", rid=req.rid, attempts=attempts["n"],
                error=f"{type(exc).__name__}: {exc}",
            )
            # the staged prefill + every handoff attempt was wasted
            # work: re-tag it before the engine's own admission opens
            # the request's next attempt (begin() is then a no-op)
            eng.tracer.degrade(req.rid, time.perf_counter())
            eng.submit(req)
            return True
        self._retried += attempts["n"] - 1
        self._stage_alloc.free(staged.pages)
        eng.admit_prefilled(req, slot, dst_pages, staged.first_token)
        self._handoffs += 1
        self._migrated_pages += n_pg
        eng.metrics.counter("serve/handoffs").inc()
        if attempts["n"] > 1:
            eng.metrics.counter("serve/handoff_retries").inc(
                attempts["n"] - 1
            )
        return True

    # ---- the tick -------------------------------------------------------

    def step(self) -> list[tuple[int, tuple[int, ...]]]:
        """One disaggregated tick: stage what the prefill pool can hold,
        hand off what the decode pool can seat, decode one sweep.
        Finishes collect on the ENGINE-side buffer contract
        (``_tick_inner``'s): a stage-retired ``max_new == 1`` request
        must survive a raise-through later in the same tick — its
        token exists nowhere else at that point."""
        finished = self._finish_buf
        while self._queue:
            staged = self._stage_prefill(self._queue[0])
            if staged is None:
                break
            req = self._queue.popleft()
            if req.max_new == 1 or staged.first_token in req.stop_tokens:
                # finished at prefill (the monolithic engine's
                # evict-at-admission case): budget of one, or the first
                # token hit a stop token — nothing to decode, nothing
                # to migrate; the staged pages retire right here
                self._stage_alloc.free(staged.pages)
                self.engine._tokens_generated += 1
                if self.engine.tracer.enabled:
                    self.engine.tracer.finish(req.rid, time.perf_counter())
                finished.append((req.rid, (staged.first_token,)))
                continue
            self._handoff.append(staged)
        while self._handoff:
            if not self._try_handoff(self._handoff[0]):
                break
            self._handoff.popleft()
        finished.extend(self.engine.step())
        self._finish_buf = []
        return finished

    def run(self, requests: Sequence[Request] = (),
            max_steps: int = 100_000) -> DisaggReport:
        """Submit ``requests`` and drain everything — queue, staging,
        handoff, decode slots — to empty."""
        for r in requests:
            self.submit(r)
        outputs: dict[int, tuple[int, ...]] = {}
        eng = self.engine
        tokens0 = eng._tokens_generated
        decode0, prefill0 = eng._decode_steps, eng._prefill_count
        prefill_s0, decode_s0 = eng._prefill_s, eng._decode_s
        slot0, drafted0 = eng._slot_steps, eng._spec_drafted
        accepted0 = eng._spec_accepted
        eptok0, estok0 = eng._prefill_tokens, eng._shared_tokens
        efresh0, ecow0 = eng._fresh_tokens, eng._cow_pages
        espill0, epref0 = eng.host_spilled_pages, eng.host_prefetched_pages
        ecold0 = eng._cold_hits
        quarantined0 = set(eng._quarantined)
        stage0, stok0 = self._stage_count, self._stage_tokens
        hand0, deg0 = self._handoffs, self._degraded
        retr0, mig0 = self._retried, self._migrated_pages
        steps = 0
        while (self._queue or self._handoff or self.engine.n_queued
               or self.engine.n_active):
            if steps >= max_steps:
                raise RuntimeError(
                    f"disagg engine did not drain in {max_steps} steps "
                    f"({self.n_queued} queued, {self.n_staged} staged, "
                    f"{self.n_active} active)"
                )
            for rid, toks in self.step():
                outputs[rid] = toks
            steps += 1
        # the full ServeEngine.run baseline set, so EVERY field of the
        # wrapped report is a this-drain delta (a reused DisaggEngine's
        # second report must not carry the first drain's counters) and
        # a degraded request quarantined by the decode side shows up
        report = eng._report(outputs, tokens0, decode0, prefill0,
                             prefill_s0, decode_s0, slot0, drafted0,
                             accepted0,
                             tuple(sorted(set(eng._quarantined)
                                          - quarantined0)),
                             eptok0, estok0, efresh0, ecow0,
                             espill0, epref0, ecold0)
        out = DisaggReport(
            engine=report,
            stage_prefills=self._stage_count - stage0,
            stage_prefill_tokens=self._stage_tokens - stok0,
            handoffs=self._handoffs - hand0,
            migrated_pages=self._migrated_pages - mig0,
            handoff_retries=self._retried - retr0,
            degraded=self._degraded - deg0,
            handoff_wire_bytes=self.handoff_wire_bytes
            * (self._handoffs - hand0),
        )
        eng.sink.emit(
            "serve/disagg_report",
            completed=out.completed, tokens_generated=out.tokens_generated,
            stage_prefills=out.stage_prefills,
            stage_prefill_tokens=out.stage_prefill_tokens,
            handoffs=out.handoffs, migrated_pages=out.migrated_pages,
            handoff_retries=out.handoff_retries, degraded=out.degraded,
        )
        eng.sink.flush()
        return out
