"""Token sampling: greedy, temperature, top-k — per-request PRNG keys.

Stateless and deterministic by construction: the key for a request's
``i``-th generated token is ``fold_in(fold_in(key(seed), rid), i)``, so
a replayed request reproduces its tokens bit-for-bit regardless of which
decode slot it lands in or how many times the engine restarted in
between — the serving analogue of the trainer's seeded-per-step data
contract (models/trainer.py).  Independence from which OTHER requests
share the batch additionally needs the no-drop capacity regime
(``capacity_factor >= n_experts``): under binding capacity, MoE routing
is batch-dependent by design (serve/decode.py keeps *idle* slots out of
that competition, so only real co-batched tokens can matter).

``temperature == 0`` is exact greedy (argmax, no key consumed);
``top_k > 0`` renormalizes over the k largest logits before the
categorical draw. Both are trace-time (static) switches, so an engine
with fixed sampling parameters compiles its sampler exactly once.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpuscratch.parallel.scores import NEG_INF


def request_key(seed: int, rid: int, position: int) -> jax.Array:
    """The PRNG key for request ``rid``'s ``position``-th generated token."""
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.key(seed), rid), position
    )


@jax.jit
def request_keys(seed_key: jax.Array, rids: jax.Array,
                 positions: jax.Array) -> jax.Array:
    """Vectorized :func:`request_key` for a whole slot bank: (B,) rids x
    (B,) positions -> (B,) keys in ONE dispatch.  The per-slot fold_in
    chain is identical to the scalar form, so scalar replay and batched
    serving draw the same streams — but the engine's decode tick pays
    one compiled call instead of ~3 tiny dispatches per slot (idle slots
    included), which would otherwise sit inside the latency-measured
    window."""
    return jax.vmap(
        lambda r, p: jax.random.fold_in(jax.random.fold_in(seed_key, r), p)
    )(rids, positions)


def sample_logits(key: jax.Array, logits: jax.Array,
                  temperature: float = 0.0, top_k: int = 0) -> jax.Array:
    """One next-token draw from a (V,) logit row. int32 token id."""
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / temperature
    if top_k:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1]
        scaled = jnp.where(scaled >= kth, scaled, NEG_INF)
    return jax.random.categorical(key, scaled).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("temperature", "top_k"))
def sample_batch(keys: jax.Array, logits: jax.Array,
                 temperature: float = 0.0, top_k: int = 0) -> jax.Array:
    """Batched draw: keys (B,) typed PRNG keys, logits (B, V) -> (B,) int32.
    Each row uses its own key, so slot placement cannot couple requests."""
    return jax.vmap(
        lambda k, l: sample_logits(k, l, temperature, top_k)
    )(keys, logits)
