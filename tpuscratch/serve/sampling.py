"""Token sampling: greedy, temperature, top-k — per-request PRNG keys.

Stateless and deterministic by construction: the key for a request's
``i``-th generated token is ``fold_in(fold_in(key(seed), rid), i)``, so
a replayed request reproduces its tokens bit-for-bit regardless of which
decode slot it lands in or how many times the engine restarted in
between — the serving analogue of the trainer's seeded-per-step data
contract (models/trainer.py).  Independence from which OTHER requests
share the batch additionally needs the no-drop capacity regime
(``capacity_factor >= n_experts``): under binding capacity, MoE routing
is batch-dependent by design (serve/decode.py keeps *idle* slots out of
that competition, so only real co-batched tokens can matter).

``temperature == 0`` is exact greedy (argmax, no key consumed);
``top_k > 0`` renormalizes over the k largest logits before the
categorical draw. Both are trace-time (static) switches, so an engine
with fixed sampling parameters compiles its sampler exactly once.

**Speculative acceptance** (:func:`accept_speculative`): the verify
step scores every draft position in one forward; this module decides
which prefix to keep.  The rule is Leviathan et al. 2023 rejection
sampling specialized to a POINT-MASS proposal (the prompt-lookup draft
is deterministic): accept draft token ``d`` with probability ``p(d)``
under the target distribution, and on rejection sample from ``p`` with
``d`` removed and renormalized — the emitted marginal is exactly ``p``
at every position, so speculation never changes the sampling
distribution.  Under greedy it degenerates to ``argmax == d``, making
speculative output BIT-IDENTICAL to non-speculative.  Accept/reject
draws key off ``fold_in(request_key(seed, rid, position), sub)`` — the
same replay-stable contract as the base sampler — and the terminal draw
(the token after the accepted prefix) uses the PLAIN ``request_key``
stream, so a slot whose draft is empty consumes exactly the draws the
non-speculative path would.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from tpuscratch.parallel.scores import NEG_INF

#: fold_in subkeys for the speculative accept/reject path (0 is implicitly
#: the base sampler's stream: request_key itself)
_SUB_ACCEPT = 1
_SUB_RESAMPLE = 2


def request_key(seed: int, rid: int, position: int) -> jax.Array:
    """The PRNG key for request ``rid``'s ``position``-th generated token."""
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.key(seed), rid), position
    )


@jax.jit
def request_keys(seed_key: jax.Array, rids: jax.Array,
                 positions: jax.Array) -> jax.Array:
    """Vectorized :func:`request_key` for a whole slot bank: (B,) rids x
    (B,) positions -> (B,) keys in ONE dispatch.  The per-slot fold_in
    chain is identical to the scalar form, so scalar replay and batched
    serving draw the same streams — but the engine's decode tick pays
    one compiled call instead of ~3 tiny dispatches per slot (idle slots
    included), which would otherwise sit inside the latency-measured
    window."""
    return jax.vmap(
        lambda r, p: jax.random.fold_in(jax.random.fold_in(seed_key, r), p)
    )(rids, positions)


def sample_logits(key: jax.Array, logits: jax.Array,
                  temperature: float = 0.0, top_k: int = 0) -> jax.Array:
    """One next-token draw from a (V,) logit row. int32 token id."""
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / temperature
    if top_k:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1]
        scaled = jnp.where(scaled >= kth, scaled, NEG_INF)
    return jax.random.categorical(key, scaled).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("temperature", "top_k"))
def sample_batch(keys: jax.Array, logits: jax.Array,
                 temperature: float = 0.0, top_k: int = 0) -> jax.Array:
    """Batched draw: keys (B,) typed PRNG keys, logits (B, V) -> (B,) int32.
    Each row uses its own key, so slot placement cannot couple requests."""
    return jax.vmap(
        lambda k, l: sample_logits(k, l, temperature, top_k)
    )(keys, logits)


# ---- speculative acceptance ----------------------------------------------


def accept_key(seed: int, rid: int, position: int) -> jax.Array:
    """PRNG key for the accept/reject uniform at one draft position."""
    return jax.random.fold_in(request_key(seed, rid, position), _SUB_ACCEPT)


@jax.jit
def _accept_uniforms(seed_key: jax.Array, rid: jax.Array,
                     positions: jax.Array) -> jax.Array:
    """Every accept/reject uniform for one verify sweep in ONE dispatch:
    (n,) positions -> (n,) uniforms, each drawn under the same fold_in
    chain as the scalar :func:`accept_key` spelling (vmap does not
    change PRNG bits), so batching is invisible to replay.  Without
    this, a temperature>0 sweep pays ~4 tiny device dispatches per
    draft position per slot INSIDE the latency-measured tick — the
    same overhead :func:`request_keys` exists to keep out of the
    window.  One compile per draft length (bounded by spec_k + 1)."""
    def one(pos):
        base = jax.random.fold_in(jax.random.fold_in(seed_key, rid), pos)
        return jax.random.uniform(jax.random.fold_in(base, _SUB_ACCEPT))
    return jax.vmap(one)(positions)


def resample_key(seed: int, rid: int, position: int) -> jax.Array:
    """PRNG key for the residual (post-rejection) categorical draw."""
    return jax.random.fold_in(request_key(seed, rid, position), _SUB_RESAMPLE)


def target_probs(logits: np.ndarray, temperature: float,
                 top_k: int = 0) -> np.ndarray:
    """The probability vector :func:`sample_logits` draws from,
    materialized (host-side fp32): softmax of ``logits / temperature``
    restricted to the top-k support — ties at the k-th logit kept, the
    same >= rule as the device sampler, so acceptance probabilities and
    base-sampler draws refer to the SAME distribution."""
    if temperature <= 0.0:
        raise ValueError(f"temperature must be > 0, got {temperature}")
    scaled = np.asarray(logits, np.float32) / np.float32(temperature)
    if top_k:
        kth = np.sort(scaled)[-top_k]
        scaled = np.where(scaled >= kth, scaled, np.float32(NEG_INF))
    scaled = scaled - scaled.max()
    e = np.exp(scaled)
    return e / e.sum()


@functools.partial(jax.jit, static_argnames=("temperature", "top_k"))
def accept_batch(seed_key: jax.Array, rids: jax.Array, pos0: jax.Array,
                 logits: jax.Array, drafts: jax.Array,
                 draft_len: jax.Array, temperature: float = 0.0,
                 top_k: int = 0) -> tuple[jax.Array, jax.Array]:
    """Batched DEVICE-side :func:`accept_speculative` for a whole slot
    bank (ISSUE 19): decide every slot's verify sweep in one compiled
    program, so the Leviathan accept/resample rule can live inside the
    macro scan carry instead of forcing a host round trip per
    speculation round.

    ``logits`` (B, K, V) — row ``j`` of slot ``b`` scores the position
    after accepting ``j`` draft tokens; ``drafts`` (B, K-1) with
    ``draft_len`` (B,) live tokens per slot; ``pos0`` (B,) — each
    slot's generated-stream index for the round's first emitted token.
    Returns ``(n_accepted (B,), terminal (B,))`` int32: the accepted
    draft prefix length and the one extra token the surviving position
    emits (residual-resampled correction on rejection, base-sampler
    bonus after a full accept).

    PRNG contract: identical fold_in chains to the host rule —
    accept uniforms off ``fold_in(request_key(seed, rid, pos0+j),
    _SUB_ACCEPT)``, the residual categorical off ``_SUB_RESAMPLE``,
    the bonus off the plain ``request_key`` stream — so replay keys
    match position for position.  Greedy (``temperature == 0``) is
    pure argmax-equality, bit-identical to the host path; at
    temperature > 0 the acceptance thresholds come from the device
    softmax where the host rule materializes a numpy one — same
    distribution, documented host-vs-device exp ulp tolerance (greedy
    is the bit-pinned contract)."""
    B, K, _ = logits.shape
    k = drafts.shape[1]
    jk = jnp.arange(k)
    if temperature == 0.0:
        am = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        ok = (am[:, :k] == drafts) & (jk[None, :] < draft_len[:, None])
        n_acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
        term = jnp.take_along_axis(am, n_acc[:, None], axis=1)[:, 0]
        return n_acc.astype(jnp.int32), term
    scaled = logits.astype(jnp.float32) / temperature
    if top_k:
        kth = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled >= kth, scaled, NEG_INF)
    probs = jax.nn.softmax(scaled, axis=-1)

    def one(rid, p0, pr, scl, d, dl):
        def u_of(j):
            base = jax.random.fold_in(
                jax.random.fold_in(seed_key, rid), p0 + j
            )
            return jax.random.uniform(
                jax.random.fold_in(base, _SUB_ACCEPT)
            )
        us = jax.vmap(u_of)(jk)
        pd = jnp.take_along_axis(pr[:k], d[:, None], axis=1)[:, 0]
        ok = (us < pd) & (jk < dl)
        n_acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32)))
        # rejection terminal: residual distribution at position n_acc
        d_rej = d[jnp.clip(n_acc, 0, k - 1)]
        res = pr[n_acc].at[d_rej].set(0.0)
        tot = jnp.sum(res)
        lg = jnp.where(res > 0.0, jnp.log(res), NEG_INF)
        base = jax.random.fold_in(
            jax.random.fold_in(seed_key, rid), p0 + n_acc
        )
        tok_rej = jnp.where(
            tot > 0.0,
            jax.random.categorical(
                jax.random.fold_in(base, _SUB_RESAMPLE), lg
            ).astype(jnp.int32),
            d_rej,
        )
        # full-accept bonus: the base sampler's draw at pos0 + n_acc
        tok_bonus = jax.random.categorical(
            base, scl[n_acc]
        ).astype(jnp.int32)
        term = jnp.where(n_acc < dl, tok_rej, tok_bonus)
        return n_acc.astype(jnp.int32), term

    return jax.vmap(one)(rids, pos0, probs, scaled, drafts, draft_len)


def accept_speculative(
    seed: int,
    rid: int,
    position0: int,
    logits,
    draft,
    temperature: float = 0.0,
    top_k: int = 0,
) -> tuple[int, tuple[int, ...]]:
    """Decide one slot's verify sweep: which draft prefix survives, and
    the one extra token the surviving position emits.

    ``logits`` — (>= len(draft)+1, V) target logits from the verify
    forward: row ``j`` scores the position after accepting ``j`` draft
    tokens.  ``position0`` — the generated-stream index of the first
    token this sweep emits (keys the draws, exactly like the base
    sampler's ``position``).  Returns ``(n_accepted, tokens)`` with
    ``len(tokens) == n_accepted + 1``: the accepted draft prefix plus
    the terminal token — the correction token sampled from the residual
    distribution at the first rejection, or the bonus token after a
    fully-accepted draft.  The terminal draw after the accepted prefix
    ``a`` uses ``request_key(seed, rid, position0 + a)`` — the plain
    per-position stream — so an empty draft reproduces the
    non-speculative draw bit-for-bit at any temperature, and greedy
    (``temperature == 0``) is pure argmax at every position.

    Distribution identity (point-mass proposal ``q = δ_d``): accept with
    ``min(1, p(d)/q(d)) = p(d)``; on reject sample from
    ``norm((p - q)^+)`` = ``p`` with ``d`` zeroed, renormalized.  The
    marginal is ``p(d)·δ_d + (1 - p(d))·p(·|≠d) = p``.
    """
    logits = np.asarray(logits, np.float32)
    draft = tuple(int(t) for t in draft)
    if logits.ndim != 2 or logits.shape[0] < len(draft) + 1:
        raise ValueError(
            f"need {len(draft) + 1} logit rows, got {logits.shape}"
        )
    if temperature == 0.0:
        am = np.argmax(logits, axis=-1)
        a = 0
        while a < len(draft) and int(am[a]) == draft[a]:
            a += 1
        return a, draft[:a] + (int(am[a]),)
    us = np.asarray(_accept_uniforms(
        jax.random.key(seed), jnp.int32(rid),
        jnp.arange(position0, position0 + len(draft), dtype=jnp.int32),
    )) if draft else ()
    a = 0
    for j, d in enumerate(draft):
        p = target_probs(logits[j], temperature, top_k)
        if us[j] < p[d]:
            a += 1
            continue
        # reject: the residual distribution is p with d removed
        res = p.copy()
        res[d] = 0.0
        tot = float(res.sum())
        if tot <= 0.0:
            # p was (numerically) a point mass at d yet the draw landed
            # in the zero-width tail: emitting d keeps the marginal
            tok = d
        else:
            lg = jnp.where(jnp.asarray(res) > 0.0,
                           jnp.log(jnp.asarray(res)), NEG_INF)
            tok = int(jax.random.categorical(
                resample_key(seed, rid, position0 + j), lg
            ))
        return a, draft[:a] + (tok,)
    # every draft token accepted: the bonus draw is the base sampler's
    tok = int(sample_logits(
        request_key(seed, rid, position0 + a), jnp.asarray(logits[a]),
        temperature, top_k,
    ))
    return a, draft[:a] + (tok,)
