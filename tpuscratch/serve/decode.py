"""Single-token decode + prompt prefill over the paged KV cache.

The inference twin of ``models/transformer.model_apply``: the same
parameter pytree, the same ``_rms_norm``/projection/MoE math, but
attention reads (and extends) the block-paged cache instead of
recomputing the whole prefix — turning the O(S) per-token forward into
O(1) compute plus an O(S) cache *gather* (``ops.attention.
decode_attention``).  Numerical equivalence to the full forward at every
position is test-gated (tests/test_serve.py) under the no-token-dropped
MoE capacity regime (capacity_factor >= n_experts), since routing is the
one component whose output can depend on which OTHER tokens share the
batch when capacity binds.

Mesh mapping (see serve/kvcache.py for the cache side):

- decode slots shard over **"dp"** (each group decodes its own slots
  against its own page pool);
- heads shard over **"sp"**: every rank projects the full q/k/v from the
  replicated weights, keeps its head slice, attends against its cached
  head slice, and the output projection psums row-blocks of ``wo`` over
  sp — Megatron-style tensor parallelism for the attention sublayer,
  which is what sequence parallelism degenerates to when the sequence
  axis is one token long;
- the MoE FFN runs the training stack's ``expert_parallel_ffn`` over
  "dp" unchanged.

Each builder returns ONE jitted program per batch shape, with a
:class:`CompileCounter` hook that increments on trace — the engine's
zero-recompile-after-warmup assertion hangs off it.  The decode step
donates the cache buffers, so steady-state decode updates pages in place
instead of copying the pool every token.

Two serving-hot-path levers compose here (both off by default):

- ``quantized=True`` stores K/V pages as int8 with per-page per-head
  scales (serve/kvcache.py): the per-token cache *write* requantizes the
  written page from its dequantized view (entries past the write offset
  — freed-page leftovers or rejected draft tokens — are zeroed so stale
  magnitudes cannot inflate a page's scale), and the per-token cache
  *read* gathers int8 pages, dequantizing after the gather
  (``ops.attention.decode_attention``) — the full-prefix sweep every
  decoded token pays moves ~1/4 the bytes;
- :func:`build_verify_step` scores ``n_draft + 1`` queued tokens per
  slot in ONE cached forward (``ops.attention.verify_attention``: one
  page gather amortized over all of them) — the verify half of
  speculative decoding, with :func:`propose_draft` as the self-drafting
  prompt-lookup proposer and ``serve.sampling.accept_speculative`` as
  the distribution-preserving acceptance rule.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.models.transformer import (
    TransformerConfig,
    _rms_norm,
    param_spec,
)
from tpuscratch.ops.attention import decode_attention, verify_attention
from tpuscratch.parallel.expert import expert_parallel_ffn
from tpuscratch.parallel.scores import masked_scores, masked_softmax
from tpuscratch.serve.kvcache import (
    CacheGeometry,
    dequantize_pages,
    kv_cache_spec,
    quantize_pages,
)
from tpuscratch.serve.sampling import (
    accept_batch,
    request_keys,
    sample_batch,
)


# promoted to the observability subsystem (recompile detection is not a
# serving-only concern — the trainer's no-retrace coverage uses it too);
# re-exported here so serve-side imports keep working
from tpuscratch.obs.metrics import CompileCounter  # noqa: F401,E402


def plan_sweep_waves(needs: Sequence[tuple[int, int, frozenset]],
                     capacity: int,
                     reorder: bool = True) -> list[list[int]]:
    """Partition sweeping slots into WAVES whose page footprints fit
    the device pool together — the tiered-KV sweep scheduler (ISSUE
    13): with a host tier holding more resident context than HBM, one
    engine tick runs several compiled sweeps, each over the slot subset
    whose frontier pages are device-resident, while the NEXT wave's
    pages prefetch behind the running one (the halo driver's
    double-buffered exchange/compute overlap applied to H2D DMA).

    ``needs`` is ``(slot, group, frozenset_of_logical_pages)`` per
    sweeping slot in slot order; ``capacity`` is one group's device
    page count.  Packing counts each group's UNIQUE pages
    (prefix-shared pages cost their footprint once).  A single slot
    wider than the pool still gets its own wave: admission guarantees
    one sequence fits the device pool (``max_seq`` check), so the
    per-slot need can never exceed ``capacity``.

    ``reorder`` (default, the ISSUE-14 wave-aware batch reordering):
    each wave is seeded with the first unplaced slot and then GREEDILY
    grown by the slot sharing the most pages with it (ties: fewest
    fresh pages added, then lowest slot id) — co-resident slots
    (prefix-shared chains, parked-and-restored siblings) pack into the
    same wave instead of being split by slot order, so a tick runs
    fewer waves and moves fewer H2D/D2H round trips.  Deterministic (a
    replayed tick partitions identically), and wave composition cannot
    change any slot's output — each slot's sweep depends only on its
    own pages and PRNG draws.  ``reorder=False`` is the legacy
    slot-order first-fit; the engine plans both and ledger-counts the
    waves the reorder saved.  Waves are returned slot-sorted."""
    if not reorder:
        waves: list[list[int]] = []
        cur: list[int] = []
        cur_pages: dict[int, set] = {}
        for slot, group, pages in needs:
            have = cur_pages.get(group, set())
            merged = have | pages
            if cur and len(merged) > capacity:
                waves.append(cur)
                cur, cur_pages = [], {}
                merged = set(pages)
            cur.append(slot)
            cur_pages[group] = merged
        if cur:
            waves.append(cur)
        return waves
    remaining = list(needs)
    waves = []
    while remaining:
        slot, group, pages = remaining.pop(0)
        cur = [slot]
        cur_pages = {group: set(pages)}
        while True:
            best = None  # (overlap, -added, -idx) maximized
            for idx, (s, g, pg) in enumerate(remaining):
                have = cur_pages.get(g, set())
                merged = have | pg
                if len(merged) > capacity:
                    continue
                key = (len(have & pg), -(len(merged) - len(have)), -idx)
                if best is None or key > best[0]:
                    best = (key, idx)
            if best is None:
                break
            s, g, pg = remaining.pop(best[1])
            cur.append(s)
            cur_pages[g] = cur_pages.get(g, set()) | pg
        waves.append(sorted(cur))
    return waves


def check_serve_mesh(mesh: Mesh, cfg: TransformerConfig,
                     dp: str = "dp", sp: str = "sp") -> None:
    """The serve-side mesh preconditions (decode and prefill share them)."""
    if cfg.n_experts % mesh.shape[dp]:
        raise ValueError(
            f"n_experts {cfg.n_experts} not divisible by dp size "
            f"{mesh.shape[dp]}"
        )
    if cfg.n_heads % mesh.shape[sp]:
        raise ValueError(
            f"serving shards heads over sp: n_heads {cfg.n_heads} not "
            f"divisible by sp size {mesh.shape[sp]}"
        )


def _check_geometry(cfg: TransformerConfig, geom: CacheGeometry) -> None:
    """A cache built for a different model fails loudly at build time,
    not as a shape error inside the compiled step."""
    if (geom.n_layers, geom.n_heads, geom.d_head) != (
        cfg.n_layers, cfg.n_heads, cfg.d_head
    ):
        raise ValueError(
            f"cache geometry (layers={geom.n_layers}, heads={geom.n_heads}, "
            f"d_head={geom.d_head}) does not match the model "
            f"(layers={cfg.n_layers}, heads={cfg.n_heads}, "
            f"d_head={cfg.d_head})"
        )


def _head_slice(t, sp: str, n_heads: int):
    """This sp rank's head slice of a (..., n_heads, d_head) projection."""
    n = lax.axis_size(sp)
    h_loc = n_heads // n
    return lax.dynamic_slice_in_dim(
        t, lax.axis_index(sp) * h_loc, h_loc, axis=t.ndim - 2
    )


def _attn_residual(p, attn_loc, x, cfg: TransformerConfig, sp: str):
    """Output projection of this rank's head slice: its row block of the
    replicated ``wo`` + psum over sp assembles the full projection."""
    n = lax.axis_size(sp)
    rows_loc = (cfg.n_heads // n) * cfg.d_head
    wo_rows = lax.dynamic_slice_in_dim(
        p["wo"], lax.axis_index(sp) * rows_loc, rows_loc, axis=0
    )
    flat = attn_loc.reshape(*attn_loc.shape[:-2], rows_loc)
    return x + lax.psum(flat @ wo_rows, sp)


def _moe_residual(p, x, cfg: TransformerConfig, dp: str):
    h = _rms_norm(x, p["ln2"])
    moe, _ = expert_parallel_ffn(
        h, p["gate"], p["w_in"], p["w_out"], dp,
        capacity_factor=cfg.capacity_factor,
    )
    return x + moe


def _quant_write(pages_q, scales, li, write_page, write_off, new_vals):
    """One quantized token write per slot: insert ``new_vals`` (B, H, D)
    at (``write_page``, ``write_off``) of layer ``li``'s int8 pool,
    requantizing each touched page.

    The page is rebuilt from its dequantized view with entries BEYOND
    the write offset zeroed: a sequence fills its pages in order, so
    offsets past the write are never live data — they are freed-page
    leftovers or rejected draft tokens, and letting them into the page's
    absmax would permanently inflate its scale.  Entries below the
    offset requantize idempotently while the scale is unchanged (q ->
    q*s -> q), and a page's absmax is monotone over its lifetime (the
    maximal entry dequantizes exactly), so each entry is requantized at
    most once per scale growth.  Sentinel write pages (idle slots,
    beyond-draft positions) gather a clipped page but scatter with drop
    mode — no write lands.

    Dtype-generic over the quantized rungs: the target rung is read
    off the pool itself (``pages_q.dtype`` — int8 or fp8-e4m3), so the
    fp8 ladder extension is a new rung through this unchanged
    mechanism, not a second write path."""
    n_pages, page_size = pages_q.shape[1], pages_q.shape[2]
    idx = jnp.clip(write_page, 0, n_pages - 1)
    pg = dequantize_pages(pages_q[li, idx], scales[li, idx])  # (B,pg,H,D)
    offs = jnp.arange(page_size)[None, :, None, None]
    wo = write_off[:, None, None, None]
    pg = jnp.where(offs == wo, new_vals[:, None],
                   jnp.where(offs < wo, pg, 0.0))
    q, s = quantize_pages(pg, pages_q.dtype)
    pages_q = pages_q.at[li, write_page].set(q, mode="drop")
    scales = scales.at[li, write_page].set(s, mode="drop")
    return pages_q, scales


def decode_step_fn(cfg: TransformerConfig, sp: str = "sp", dp: str = "dp",
                   quantized: bool = False, fused: bool | None = None):
    """The decode shard_map body:
    (params, kv, x, page_tables, write_page, write_off, seq_lens)
    -> (out, kv').

    Local shapes: x (B_loc, d) — each slot's current-token vector;
    page_tables (B_loc, max_pages) LOCAL page ids; write_page/write_off
    (B_loc,) — where this token's K/V lands (write_page >= n_pages for
    idle slots: the scatter's drop mode makes them no-ops); seq_lens
    (B_loc,) — cached length INCLUDING this token (0 idles the slot).

    ``fused`` selects the attention kernel per
    ``ops.attention.decode_attention``: None follows the backend policy
    (fused Pallas sweep on TPU, dense oracle elsewhere), True/False
    force it.
    """

    def step(params, kv, x, page_tables, write_page, write_off, seq_lens):
        kv_k, kv_v = kv["k"], kv["v"]
        k_scale = kv.get("k_scale")
        v_scale = kv.get("v_scale")
        H, Dh = cfg.n_heads, cfg.d_head
        B = x.shape[0]
        # idle slots must not compete for MoE expert capacity: routing
        # priority is positional, so an idle slot's zero vector ahead of
        # a real token would consume capacity and CHANGE that token's
        # output whenever capacity binds (capacity_factor < n_experts).
        # A stable idle-last permutation keeps the compiled shape fixed
        # while making idle tokens lose every capacity tie; jax sorts
        # are stable, so active slots keep their relative order.
        perm = jnp.argsort((seq_lens == 0).astype(jnp.int32))
        inv = jnp.argsort(perm)
        for li, p in enumerate(params["layers"]):
            h = _rms_norm(x, p["ln1"])
            q = _head_slice((h @ p["wq"]).reshape(B, H, Dh), sp, H)
            k = _head_slice((h @ p["wk"]).reshape(B, H, Dh), sp, H)
            v = _head_slice((h @ p["wv"]).reshape(B, H, Dh), sp, H)
            if quantized:
                kv_k, k_scale = _quant_write(
                    kv_k, k_scale, li, write_page, write_off, k
                )
                kv_v, v_scale = _quant_write(
                    kv_v, v_scale, li, write_page, write_off, v
                )
                attn = decode_attention(
                    q, kv_k[li], kv_v[li], page_tables, seq_lens,
                    k_scale[li], v_scale[li], fused=fused,
                )
            else:
                kv_k = kv_k.at[li, write_page, write_off].set(k, mode="drop")
                kv_v = kv_v.at[li, write_page, write_off].set(v, mode="drop")
                attn = decode_attention(
                    q, kv_k[li], kv_v[li], page_tables, seq_lens, fused=fused
                )
            x = _attn_residual(p, attn, x, cfg, sp)
            x = _moe_residual(p, x[perm], cfg, dp)[inv]
        return x, _cache_out(kv_k, kv_v, k_scale, v_scale)

    return step


def _cache_out(kv_k, kv_v, k_scale, v_scale) -> dict:
    out = {"k": kv_k, "v": kv_v}
    if k_scale is not None:
        out["k_scale"] = k_scale
        out["v_scale"] = v_scale
    return out


def build_decode_step(mesh: Mesh, cfg: TransformerConfig,
                      geom: CacheGeometry, dp: str = "dp", sp: str = "sp",
                      counter: CompileCounter | None = None,
                      quantized: bool = False, fused: bool | None = None):
    """Compiled decode step over ``mesh``: jit'd
    fn(params, kv, x, page_tables, write_page, write_off, seq_lens) ->
    (out (B, d), kv') with slots sharded P(dp) and the cache donated
    (page pools update in place).  One compile per (B, max_pages)
    bucket; the engine holds B fixed at its slot count, so steady-state
    decode never recompiles (``counter`` proves it).  ``quantized``
    selects the quantized-page cache contract (int8/fp8 pools with
    scale leaves in ``kv``); ``fused`` the attention kernel (see
    :func:`decode_step_fn`)."""
    check_serve_mesh(mesh, cfg, dp, sp)
    _check_geometry(cfg, geom)
    body = decode_step_fn(cfg, sp=sp, dp=dp, quantized=quantized,
                          fused=fused)
    if counter is not None:
        body = counter.wrap(body)
    pspec = param_spec(cfg, dp)
    kspec = kv_cache_spec(dp, sp, quantized)
    return run_spmd(
        mesh,
        body,
        (pspec, kspec, P(dp), P(dp), P(dp), P(dp), P(dp)),
        (P(dp), kspec),
        donate_argnums=(1,),
    )


# ---- device-resident macro-step decode (ISSUE 15) ------------------------


def decode_loop_fn(cfg: TransformerConfig, geom: CacheGeometry,
                   macro_steps: int, temperature: float = 0.0,
                   top_k: int = 0, sp: str = "sp", dp: str = "dp",
                   quantized: bool = False, fused: bool | None = None):
    """The macro-step shard_map body: ``macro_steps`` whole engine
    token-ticks — decode sweep, unembed, sample, quantized KV write,
    frontier/length advance — fused into ONE ``lax.scan``, so the host
    dispatches and syncs once per T tokens instead of per token (the
    ``mpicuda4.cu`` one-kernel-does-everything reduction applied to the
    serving tick; per-token host orchestration is pure badput once the
    sweep itself is cheap).

    (params, kv, embed, key_data, tables, n_cached, rids, positions,
    budgets, last_tok, stop_mask, stopped, emitted) ->
    ((T, B_loc) tokens, (T, B_loc) active mask, kv', n_cached',
    positions', last_tok', emitted', stopped').

    Local shapes: tables (B_loc, max_pages) — each slot's FULL page
    list (prompt + reserved budget tail; the write frontier advances
    into the tail inside the scan), sentinel rows for empty slots;
    n_cached (B_loc,) tokens already cached (0 idles the slot);
    rids/positions (B_loc,) — the per-request PRNG fold-in chain,
    positions advanced in-carry so draw ``i`` of a request is keyed
    identically to the per-token engine's; budgets (B_loc,) tokens this
    slot may still emit; last_tok (B_loc,) each slot's current token.
    stop_mask (B_loc, V) bool — True at each slot's stop-token ids
    (all-False rows for slots without stop tokens); stopped/emitted
    (B_loc,) — the in-carry finish flag and tokens-already-emitted
    count, passed IN (rather than zero-initialized) so the async macro
    tick can chain one scan's final carry straight into the next
    dispatch without a host round trip.  embed (V, d) and key_data (the
    engine seed key's ``jax.random.key_data``) are replicated.

    Scan-step semantics are EXACTLY one legacy engine tick, so greedy
    output is bit-identical across macro_steps:

    - a slot is ACTIVE while ``n_cached > 0``, it has budget left, and
      it has not emitted a stop token (the device-side EOS check, ISSUE
      19: a sampled token hitting the slot's ``stop_mask`` row sets the
      carried ``stopped`` flag AFTER the stop token itself is emitted,
      so the stop token appears in the output exactly as the host-side
      path records it and every later iteration sees the slot idle);
      a slot whose budget or stop token ends it mid-scan flips to the
      legacy IDLE
      contract for the remaining iterations — zero input vector,
      ``seq_len == 0`` (attention returns zeros, the MoE idle-last
      permutation sorts it out of capacity competition), sentinel
      write target (the drop-mode scatter / quantized-write drop
      suppresses its K/V write) — byte-for-byte what the per-token
      engine feeds an evicted slot's seat;
    - the write target is computed in-carry from the slot's own table
      row and frontier, so page-boundary crossings need no host;
    - sampling draws ``fold_in(fold_in(seed, rid), position)`` exactly
      as ``serve.sampling.request_keys`` does host-side.

    The in-program EARLY-EXIT mask: each iteration reduces "any slot
    active?" across the whole mesh (one scalar psum — replicated, so
    every rank takes the same branch) and an all-done bank skips the
    sweep/sample body via ``lax.cond`` instead of burning the tail of
    the scan on idle sweeps.

    The scan compiles to ONE while loop: the sweep's gather/collective
    pattern appears once in the optimized HLO and is REUSED T times
    (ledger-asserted in tests), which is why steady-state recompiles
    stay zero at any T."""
    if macro_steps < 1:
        raise ValueError(f"macro_steps must be >= 1, got {macro_steps}")
    step = decode_step_fn(cfg, sp=sp, dp=dp, quantized=quantized,
                          fused=fused)
    page_size, n_pages = geom.page_size, geom.n_pages

    def loop(params, kv, embed, key_data, tables, n_cached, rids,
             positions, budgets, last_tok, stop_mask, stopped, emitted):
        key = jax.random.wrap_key_data(key_data)
        B = tables.shape[0]

        def body(carry, _):
            kv, n_cached, positions, last_tok, emitted, stopped = carry
            active = (n_cached > 0) & (emitted < budgets) & ~stopped
            # replicated early-exit predicate: every rank must agree
            # (the MoE FFN reduces over dp, attention output over sp)
            any_active = lax.psum(
                jnp.any(active).astype(jnp.int32), (dp, sp)
            ) > 0

            def tick(ops):
                kv, n_cached, positions, last_tok, emitted, stopped = ops
                act_i = active.astype(n_cached.dtype)
                x = jnp.where(active[:, None], embed[last_tok], 0.0)
                seq = jnp.where(active, n_cached + 1, 0)
                pidx = jnp.clip(
                    n_cached // page_size, 0, tables.shape[1] - 1
                )
                wp = jnp.where(
                    active,
                    jnp.take_along_axis(tables, pidx[:, None], 1)[:, 0],
                    n_pages,
                )
                woff = jnp.where(active, n_cached % page_size, 0)
                out, kv = step(params, kv, x, tables, wp, woff, seq)
                logits = out @ embed.T
                # the ONE key-derivation chain (serve.sampling): the
                # per-token engine and this scan must draw the same
                # streams or macro bit-identity silently breaks
                keys = request_keys(key, rids, positions)
                toks = sample_batch(keys, logits, temperature=temperature,
                                    top_k=top_k)
                toks = jnp.where(active, toks, 0)
                # device-side EOS: the stop token itself is emitted
                # (this iteration's toks/mask carry it), the flag idles
                # the slot from the NEXT iteration on
                hit = jnp.take_along_axis(
                    stop_mask, toks[:, None], axis=1
                )[:, 0]
                stopped = stopped | (active & hit)
                return (
                    (kv, n_cached + act_i, positions + act_i,
                     jnp.where(active, toks, last_tok), emitted + act_i,
                     stopped),
                    toks,
                )

            def skip(ops):
                return ops, jnp.zeros((B,), jnp.int32)

            carry, toks = lax.cond(
                any_active, tick, skip,
                (kv, n_cached, positions, last_tok, emitted, stopped),
            )
            return carry, (toks, active)

        init = (kv, n_cached, positions, last_tok, emitted, stopped)
        (kv, n_cached, positions, last_tok, emitted, stopped), \
            (toks, mask) = lax.scan(body, init, None, length=macro_steps)
        return (toks, mask, kv, n_cached, positions, last_tok, emitted,
                stopped)

    return loop


def build_decode_loop(mesh: Mesh, cfg: TransformerConfig,
                      geom: CacheGeometry, macro_steps: int,
                      temperature: float = 0.0, top_k: int = 0,
                      dp: str = "dp", sp: str = "sp",
                      counter: CompileCounter | None = None,
                      quantized: bool = False, fused: bool | None = None):
    """Compiled device-resident macro-step decode over ``mesh``: jit'd
    fn(params, kv, embed, key_data, tables (B, max_pages), n_cached,
    rids, positions, budgets, last_tok — (B,) int32 — stop_mask (B, V)
    bool, stopped (B,) bool, emitted (B,) int32) ->
    (tokens (T, B), active_mask (T, B), kv', n_cached', positions',
    last_tok', emitted', stopped'), slots sharded P(dp), embed/key
    replicated, cache donated.  ONE dispatch and ONE host-sync per
    ``macro_steps`` generated tokens; the final slot-state carry comes
    BACK as device arrays, so the async macro tick can dispatch the
    next scan on it without syncing first (ISSUE 19).  The engine holds
    B fixed at its slot count and T fixed at construction, so
    steady-state macro decode never recompiles (``counter`` proves
    it).  See :func:`decode_loop_fn` for the per-iteration contract
    and the bit-identity argument."""
    check_serve_mesh(mesh, cfg, dp, sp)
    _check_geometry(cfg, geom)
    body = decode_loop_fn(
        cfg, geom, macro_steps, temperature=temperature, top_k=top_k,
        sp=sp, dp=dp, quantized=quantized, fused=fused,
    )
    if counter is not None:
        body = counter.wrap(body)
    pspec = param_spec(cfg, dp)
    kspec = kv_cache_spec(dp, sp, quantized)
    return run_spmd(
        mesh,
        body,
        (pspec, kspec, P(), P(), P(dp), P(dp), P(dp), P(dp), P(dp), P(dp),
         P(dp), P(dp), P(dp)),
        (P(None, dp), P(None, dp), kspec, P(dp), P(dp), P(dp), P(dp),
         P(dp)),
        donate_argnums=(1,),
    )


def macro_occupancy(mask) -> tuple[int, "np.ndarray"]:
    """The macro-boundary stamp: fold a macro scan's per-round activity
    mask ``(T, B)`` — the plain loop's emit mask, or ``n_emit > 0``
    under speculation — into ``(bank_rounds, per_slot_rounds)``.
    ``bank_rounds`` is the number of rounds any slot ran before the
    early-exit psum idled the bank (per-slot active masks are prefixes,
    so the longest column IS the any-active iteration count — the
    ``_decode_rounds`` rule, scan-widened); ``per_slot_rounds[s]`` is
    how many of them slot ``s`` occupied — what the request tracer
    stamps on each rid's per-macro-tick decode span."""
    m = np.asarray(mask, dtype=bool)
    return int(m.any(axis=1).sum()), m.sum(axis=0).astype(np.int64)


# ---- speculative decoding: self-drafting proposer + batched verify -------


def propose_draft(context: Sequence[int], k: int,
                  ngram: int = 2) -> tuple[int, ...]:
    """Self-drafting prompt-lookup proposal (host-side, O(len) scan):
    find the most recent EARLIER occurrence of the context's final
    ``ngram`` tokens and propose the (up to) ``k`` tokens that followed
    it.  Returns ``()`` when the context never repeats its suffix — the
    engine then degenerates to plain one-token decode for that slot.

    No draft model anywhere: the sequence drafts itself from its own
    prompt + generated history (prompt-lookup / n-gram speculation),
    which is exactly the regime where decode loops over boilerplate —
    code, templates, retrieved spans — and an HBM-bound sweep can be
    amortized over several accepted tokens.  The most recent match with
    a FULL ``k``-token continuation wins (local repetition predicts the
    immediate continuation best, and a full draft amortizes the sweep
    furthest — on a short-period context the nearest match is always
    truncated by the sequence end); a truncated continuation is the
    fallback."""
    if k < 1 or ngram < 1:
        return ()
    ctx = tuple(int(t) for t in context)
    n = len(ctx)
    if n < ngram + 1:
        return ()
    suffix = ctx[n - ngram:]
    partial: tuple[int, ...] = ()
    for i in range(n - ngram - 1, -1, -1):
        if ctx[i:i + ngram] == suffix:
            cont = ctx[i + ngram: i + ngram + k]
            if len(cont) == k:
                return cont
            if not partial:
                partial = cont
    return partial


def propose_draft_batch(hist: jax.Array, ctx_len: jax.Array, k: int,
                        ngram: int = 2) -> tuple[jax.Array, jax.Array]:
    """Device-resident :func:`propose_draft` for a whole slot bank: the
    suffix-ngram lookup as a batched gather over each slot's
    device-resident token history, so draft proposal can live INSIDE
    the macro scan carry (ISSUE 19) instead of forcing a host sync per
    speculation round.

    ``hist`` (B, S) int32 — each slot's prompt + generated tokens so
    far, zero-padded past ``ctx_len``; ``ctx_len`` (B,) — live history
    length per slot.  Returns ``(drafts (B, k) int32, draft_len (B,))``
    with tokens past each slot's draft length zeroed.

    Equivalence to the host proposer's most-recent-match descent,
    position by position: a candidate start ``i`` matches iff
    ``hist[i:i+ngram]`` equals the final ``ngram`` tokens, restricted
    to ``i <= n - ngram - 1`` (the host loop's range); the LARGEST
    matching ``i`` whose continuation is a full ``k`` tokens
    (``i <= n - ngram - k``) wins, else the largest matching ``i``
    with its truncated continuation — exactly the host rule that the
    first full match found during the high-to-low descent beats every
    partial, and the first partial is the highest-index match.  The
    comparison window reads from a ``-1``-padded copy of the history so
    out-of-range positions can never equal a real (non-negative) token
    id."""
    if k < 1 or ngram < 1:
        raise ValueError(f"need k >= 1 and ngram >= 1, got {k}, {ngram}")
    B, S = hist.shape
    pad = jnp.full((B, ngram + k), -1, hist.dtype)
    hist_pad = jnp.concatenate([hist, pad], axis=1)
    idx = jnp.arange(S)[None, :]
    n = ctx_len[:, None]
    match = jnp.ones((B, S), bool)
    for j in range(ngram):
        suffix_j = jnp.take_along_axis(
            hist, jnp.clip(n - ngram + j, 0, S - 1), axis=1
        )
        match = match & (hist_pad[:, j:j + S] == suffix_j)
    cand = match & (idx <= n - ngram - 1)
    full = cand & (idx <= n - ngram - k)
    i_part = jnp.max(jnp.where(cand, idx, -1), axis=1)
    i_full = jnp.max(jnp.where(full, idx, -1), axis=1)
    i0 = jnp.where(i_full >= 0, i_full, i_part)
    dlen = jnp.where(
        i_full >= 0, k,
        jnp.where(i_part >= 0, ctx_len - i_part - ngram, 0),
    )
    dlen = jnp.where((ctx_len >= ngram + 1) & (i0 >= 0), dlen, 0)
    gat = i0[:, None] + ngram + jnp.arange(k)[None, :]
    drafts = jnp.take_along_axis(
        hist_pad, jnp.clip(gat, 0, S + ngram + k - 1), axis=1
    )
    drafts = jnp.where(jnp.arange(k)[None, :] < dlen[:, None], drafts, 0)
    return drafts.astype(jnp.int32), dlen.astype(jnp.int32)


def verify_step_fn(cfg: TransformerConfig, n_draft: int, sp: str = "sp",
                   dp: str = "dp", quantized: bool = False,
                   fused: bool | None = None):
    """The speculative-verify shard_map body: like
    :func:`decode_step_fn` but scoring ``K = n_draft + 1`` queued tokens
    per slot in one forward —
    (params, kv, x, page_tables, write_pages, write_offs, seq_lens)
    -> (out (B_loc, K, d), kv').

    Local shapes: x (B_loc, K, d) — position 0 each slot's last accepted
    token, positions 1..n_draft its draft (zero vectors past the slot's
    true draft length); write_pages/write_offs (B_loc, K) — where each
    position's K/V lands, with the out-of-range sentinel for idle slots
    AND beyond-draft positions (drop-mode scatter / quantized-write drop
    makes them no-ops); seq_lens (B_loc,) — cached length INCLUDING
    position 0 (0 idles the slot).  All K positions' K/V are written
    BEFORE attention, so position j attends positions < seq_len + j —
    rejected positions leave garbage entries past the accepted length
    that the length mask hides and the next tick's writes overwrite
    (the next sweep starts at the accepted frontier and writes K fresh
    entries, always covering them)."""
    K = n_draft + 1

    def step(params, kv, x, page_tables, write_pages, write_offs, seq_lens):
        kv_k, kv_v = kv["k"], kv["v"]
        k_scale = kv.get("k_scale")
        v_scale = kv.get("v_scale")
        H, Dh = cfg.n_heads, cfg.d_head
        B = x.shape[0]
        n_pages = kv_k.shape[1]
        # token-level idle-last permutation (decode_step_fn's rule, per
        # TOKEN rather than per slot): a position is real iff its write
        # page is real — idle slots and beyond-draft padding carry the
        # sentinel — so padding zero-vectors lose every MoE capacity tie
        idle = (write_pages >= n_pages).reshape(B * K)
        perm = jnp.argsort(idle.astype(jnp.int32))
        inv = jnp.argsort(perm)
        for li, p in enumerate(params["layers"]):
            h = _rms_norm(x, p["ln1"])
            q = _head_slice((h @ p["wq"]).reshape(B, K, H, Dh), sp, H)
            k = _head_slice((h @ p["wk"]).reshape(B, K, H, Dh), sp, H)
            v = _head_slice((h @ p["wv"]).reshape(B, K, H, Dh), sp, H)
            if quantized:
                # sequential per position: adjacent draft positions can
                # share a page, and each requantizing write must see the
                # previous one's entries
                for j in range(K):
                    kv_k, k_scale = _quant_write(
                        kv_k, k_scale, li, write_pages[:, j],
                        write_offs[:, j], k[:, j],
                    )
                    kv_v, v_scale = _quant_write(
                        kv_v, v_scale, li, write_pages[:, j],
                        write_offs[:, j], v[:, j],
                    )
                attn = verify_attention(
                    q, kv_k[li], kv_v[li], page_tables, seq_lens,
                    k_scale[li], v_scale[li], fused=fused,
                )
            else:
                kv_k = kv_k.at[li, write_pages, write_offs].set(
                    k, mode="drop"
                )
                kv_v = kv_v.at[li, write_pages, write_offs].set(
                    v, mode="drop"
                )
                attn = verify_attention(
                    q, kv_k[li], kv_v[li], page_tables, seq_lens, fused=fused
                )
            x = _attn_residual(p, attn, x, cfg, sp)
            flat = x.reshape(B * K, cfg.d_model)
            x = _moe_residual(p, flat[perm], cfg, dp)[inv].reshape(
                B, K, cfg.d_model
            )
        return x, _cache_out(kv_k, kv_v, k_scale, v_scale)

    return step


def build_verify_step(mesh: Mesh, cfg: TransformerConfig,
                      geom: CacheGeometry, n_draft: int,
                      dp: str = "dp", sp: str = "sp",
                      counter: CompileCounter | None = None,
                      quantized: bool = False, fused: bool | None = None):
    """Compiled speculative-verify step over ``mesh``: jit'd
    fn(params, kv, x (B, K, d), page_tables, write_pages (B, K),
    write_offs (B, K), seq_lens) -> (out (B, K, d), kv'), cache donated.
    ``K = n_draft + 1`` is static — the engine fixes the draft budget at
    construction, so a speculative engine still compiles exactly ONE
    decode-side program (``counter`` proves it stays that way)."""
    if n_draft < 1:
        raise ValueError(f"n_draft must be >= 1, got {n_draft}")
    check_serve_mesh(mesh, cfg, dp, sp)
    _check_geometry(cfg, geom)
    body = verify_step_fn(cfg, n_draft, sp=sp, dp=dp, quantized=quantized,
                          fused=fused)
    if counter is not None:
        body = counter.wrap(body)
    pspec = param_spec(cfg, dp)
    kspec = kv_cache_spec(dp, sp, quantized)
    return run_spmd(
        mesh,
        body,
        (pspec, kspec, P(dp), P(dp), P(dp), P(dp), P(dp)),
        (P(dp), kspec),
        donate_argnums=(1,),
    )


def spec_decode_loop_fn(cfg: TransformerConfig, geom: CacheGeometry,
                        macro_steps: int, spec_k: int,
                        temperature: float = 0.0, top_k: int = 0,
                        ngram: int = 2, sp: str = "sp", dp: str = "dp",
                        quantized: bool = False, fused: bool | None = None):
    """The SPECULATIVE macro-step shard_map body (ISSUE 19): T whole
    speculation rounds — suffix-ngram draft proposal
    (:func:`propose_draft_batch`), the K-position verify forward
    (:func:`verify_step_fn`'s program), Leviathan accept/resample
    (``serve.sampling.accept_batch``), KV/frontier/history advance —
    fused into ONE ``lax.scan``, so ``spec_k > 0`` COMPOSES with
    ``macro_steps > 1`` instead of clamping it: one dispatch covers up
    to ``T * (spec_k + 1)`` token rounds.

    (params, kv, embed, key_data, tables, n_cached, rids, positions,
    budgets, last_tok, hist, stop_mask, stopped) ->
    ((T, B_loc, K) tokens, (T, B_loc) n_emit, (T, B_loc) draft_len,
    kv') with ``K = spec_k + 1``.

    Local shapes follow :func:`decode_loop_fn` plus: hist (B_loc, S) —
    each slot's prompt + generated token history (the proposer's
    gather window, length ``n_cached + 1`` live entries including the
    current token), extended in-carry as tokens are accepted;
    stop_mask (B_loc, V) / stopped (B_loc,) — the device-side EOS
    state.  Row ``r`` of the outputs is round ``r``: the slot emitted
    ``n_emit[r, s]`` tokens (``tokens[r, s, :n_emit[r, s]]`` — the
    accepted draft prefix plus the terminal token, truncated at a stop
    hit) after proposing ``draft_len[r, s]`` draft tokens.

    Round semantics are EXACTLY one legacy ``_spec_sweep`` tick, so
    greedy output is bit-identical across macro_steps:

    - the draft is clamped to ``remaining_budget - 1`` (the sweep can
      emit at most ``draft_len + 1``, never past the budget) and to
      the host proposer's gating;
    - position 0 scores the slot's current token, positions 1..dlen
      its draft; beyond-draft positions carry zero vectors and the
      write sentinel (the verify step's padding contract);
    - acceptance draws key off the SAME
      ``fold_in(request_key, _SUB_ACCEPT/_SUB_RESAMPLE)`` chains as
      the host rule, with ``position0 = positions`` (the
      generated-stream index of the round's first emitted token);
      greedy is pure argmax — the bit-pinned contract;
    - a stop token anywhere in the emitted run truncates it there
      (``n_emit`` shrinks to include the stop token) and idles the
      slot — the device-side EOS rule;
    - rejected-draft and post-stop KV entries follow the legacy
      verify-step garbage contract: length-masked now, overwritten by
      the next round's K fresh writes at the accepted frontier.

    The same replicated early-exit psum as the plain loop skips
    all-done iterations.  ``emitted`` is zero-initialized here (the
    spec path never async-chains: its per-round token count is
    data-dependent, so the host must read ``n_emit`` before it can
    know completion)."""
    if macro_steps < 1:
        raise ValueError(f"macro_steps must be >= 1, got {macro_steps}")
    if spec_k < 1:
        raise ValueError(f"spec_k must be >= 1, got {spec_k}")
    K = spec_k + 1
    step = verify_step_fn(cfg, spec_k, sp=sp, dp=dp, quantized=quantized,
                          fused=fused)
    page_size, n_pages = geom.page_size, geom.n_pages

    def loop(params, kv, embed, key_data, tables, n_cached, rids,
             positions, budgets, last_tok, hist, stop_mask, stopped):
        key = jax.random.wrap_key_data(key_data)
        B = tables.shape[0]
        S = hist.shape[1]
        jpos = jnp.arange(K)[None, :]

        def body(carry, _):
            kv, hist, n_cached, positions, last_tok, emitted, stopped = carry
            active = (n_cached > 0) & (emitted < budgets) & ~stopped
            any_active = lax.psum(
                jnp.any(active).astype(jnp.int32), (dp, sp)
            ) > 0

            def tick(ops):
                kv, hist, n_cached, positions, last_tok, emitted, \
                    stopped = ops
                ctx_len = n_cached + 1
                drafts, dlen = propose_draft_batch(
                    hist, ctx_len, spec_k, ngram
                )
                # the sweep emits n_acc + 1 <= dlen + 1 tokens: clamp
                # the draft so a slot can never overrun its budget
                remaining = budgets - emitted
                dlen = jnp.minimum(dlen, remaining - 1)
                dlen = jnp.where(active, jnp.maximum(dlen, 0), 0)
                drafts = jnp.where(
                    jnp.arange(spec_k)[None, :] < dlen[:, None], drafts, 0
                )
                toks_in = jnp.concatenate(
                    [last_tok[:, None], drafts], axis=1
                )
                live = active[:, None] & (jpos <= dlen[:, None])
                x = jnp.where(live[..., None], embed[toks_in], 0.0)
                wpos = n_cached[:, None] + jpos
                pidx = jnp.clip(wpos // page_size, 0, tables.shape[1] - 1)
                wp = jnp.where(
                    live, jnp.take_along_axis(tables, pidx, axis=1),
                    n_pages,
                )
                woff = jnp.where(live, wpos % page_size, 0)
                seq = jnp.where(active, n_cached + 1, 0)
                out, kv = step(params, kv, x, tables, wp, woff, seq)
                logits = out @ embed.T
                n_acc, term = accept_batch(
                    key, rids, positions, logits, drafts, dlen,
                    temperature=temperature, top_k=top_k,
                )
                n_acc = jnp.where(active, n_acc, 0)
                term = jnp.where(active, term, 0)
                drafts_pad = jnp.concatenate(
                    [drafts, jnp.zeros((B, 1), drafts.dtype)], axis=1
                )
                toks_k = jnp.where(
                    jpos < n_acc[:, None], drafts_pad,
                    jnp.where(jpos == n_acc[:, None], term[:, None], 0),
                )
                toks_k = jnp.where(active[:, None], toks_k, 0)
                n_emit = jnp.where(active, n_acc + 1, 0)
                # device-side EOS: truncate the emitted run at the
                # first stop hit (the stop token itself is kept)
                is_stop = jnp.take_along_axis(
                    stop_mask, toks_k, axis=1
                ) & (jpos < n_emit[:, None])
                has_stop = jnp.any(is_stop, axis=1)
                j_stop = jnp.argmax(is_stop, axis=1)
                n_emit = jnp.where(has_stop, j_stop + 1, n_emit)
                toks_k = jnp.where(jpos < n_emit[:, None], toks_k, 0)
                stopped = stopped | (active & has_stop)
                # extend the proposer's history window in-carry
                wpos_h = jnp.where(
                    jpos < n_emit[:, None], ctx_len[:, None] + jpos, S
                )
                hist = jax.vmap(
                    lambda h, p, t: h.at[p].set(t, mode="drop")
                )(hist, wpos_h, toks_k)
                last_idx = jnp.clip(n_emit - 1, 0, K - 1)
                new_last = jnp.take_along_axis(
                    toks_k, last_idx[:, None], axis=1
                )[:, 0]
                last_tok = jnp.where(active, new_last, last_tok)
                return (
                    (kv, hist, n_cached + n_emit, positions + n_emit,
                     last_tok, emitted + n_emit, stopped),
                    (toks_k, n_emit, dlen),
                )

            def skip(ops):
                return ops, (jnp.zeros((B, K), jnp.int32),
                             jnp.zeros((B,), jnp.int32),
                             jnp.zeros((B,), jnp.int32))

            carry, out = lax.cond(
                any_active, tick, skip,
                (kv, hist, n_cached, positions, last_tok, emitted,
                 stopped),
            )
            return carry, out

        init = (kv, hist, n_cached, positions, last_tok,
                jnp.zeros_like(budgets), stopped)
        (kv, *_), (toks, n_emit, dlen) = lax.scan(
            body, init, None, length=macro_steps
        )
        return toks, n_emit, dlen, kv

    return loop


def build_spec_decode_loop(mesh: Mesh, cfg: TransformerConfig,
                           geom: CacheGeometry, macro_steps: int,
                           spec_k: int, temperature: float = 0.0,
                           top_k: int = 0, ngram: int = 2,
                           dp: str = "dp", sp: str = "sp",
                           counter: CompileCounter | None = None,
                           quantized: bool = False,
                           fused: bool | None = None):
    """Compiled device-resident SPECULATIVE macro-step decode over
    ``mesh``: jit'd fn(params, kv, embed, key_data, tables
    (B, max_pages), n_cached, rids, positions, budgets, last_tok —
    (B,) int32 — hist (B, S) int32, stop_mask (B, V) bool, stopped (B,)
    bool) -> (tokens (T, B, K), n_emit (T, B), draft_len (T, B), kv'),
    slots sharded P(dp), embed/key replicated, cache donated.  ONE
    dispatch and ONE host-sync per T speculation rounds — up to
    ``T * (spec_k + 1)`` tokens; B, T and K are fixed at construction,
    so steady-state speculative macro decode never recompiles
    (``counter`` proves it).  See :func:`spec_decode_loop_fn` for the
    per-round contract and the bit-identity argument."""
    check_serve_mesh(mesh, cfg, dp, sp)
    _check_geometry(cfg, geom)
    body = spec_decode_loop_fn(
        cfg, geom, macro_steps, spec_k, temperature=temperature,
        top_k=top_k, ngram=ngram, sp=sp, dp=dp, quantized=quantized,
        fused=fused,
    )
    if counter is not None:
        body = counter.wrap(body)
    pspec = param_spec(cfg, dp)
    kspec = kv_cache_spec(dp, sp, quantized)
    return run_spmd(
        mesh,
        body,
        (pspec, kspec, P(), P(), P(dp), P(dp), P(dp), P(dp), P(dp), P(dp),
         P(dp), P(dp), P(dp)),
        (P(None, dp), P(None, dp), P(None, dp), kspec),
        donate_argnums=(1,),
    )


def build_context_prefill(mesh: Mesh, cfg: TransformerConfig,
                          geom: CacheGeometry, chunk: int,
                          dp: str = "dp", sp: str = "sp",
                          counter: CompileCounter | None = None,
                          quantized: bool = False,
                          fused: bool | None = None):
    """Compiled CONTEXT prefill over ``mesh``: a slot-banked program
    scoring up to ``chunk`` new prompt tokens per slot against the
    slot's already-cached prefix — jit'd fn(params, kv, x (B, chunk, d),
    page_tables, write_pages (B, chunk), write_offs (B, chunk),
    seq_lens) -> (out (B, chunk, d), kv'), cache donated.

    This is :func:`verify_step_fn`'s program pointed at prefill instead
    of speculation — the realization that chunked prefill and
    speculative verify are the SAME compiled shape: K queued tokens per
    slot, K/V written before attention, position ``j`` ragged-causally
    attending the first ``seq_len + j`` cache entries
    (``ops.attention.verify_attention``: one page gather amortized over
    the whole chunk).  Two serving layers ride it:

    - **chunked prefill**: a long prompt advances ``chunk`` tokens per
      engine tick instead of monopolizing one tick for its whole
      length, so resident decode streams keep their per-token cadence
      (``seq_lens = n_cached + 1`` makes the first chunk degenerate to
      plain causal self-attention — nothing cached yet);
    - **prefix-shared admission**: a prompt whose full-page prefix was
      matched in the :class:`~tpuscratch.serve.kvcache.PrefixCache`
      prefills only its TAIL through this program, attending the
      shared pages it never recomputed.

    Tokens past a slot's real chunk length carry the out-of-range write
    sentinel (drop-mode scatter / quantized-write drop) and zero
    vectors, and the token-level idle-last MoE permutation keeps that
    padding out of expert capacity competition — the verify step's
    contract, unchanged.  ``chunk >= 1``: unlike ``build_verify_step``
    (which needs a draft to verify), a one-token chunk is legitimate —
    it is exactly the re-score step a fully-shared aligned prompt pays
    for its last-position logits."""
    if chunk < 1:
        raise ValueError(f"chunk must be >= 1, got {chunk}")
    check_serve_mesh(mesh, cfg, dp, sp)
    _check_geometry(cfg, geom)
    body = verify_step_fn(cfg, chunk - 1, sp=sp, dp=dp, quantized=quantized,
                          fused=fused)
    if counter is not None:
        body = counter.wrap(body)
    pspec = param_spec(cfg, dp)
    kspec = kv_cache_spec(dp, sp, quantized)
    return run_spmd(
        mesh,
        body,
        (pspec, kspec, P(dp), P(dp), P(dp), P(dp), P(dp)),
        (P(dp), kspec),
        donate_argnums=(1,),
    )


def prefill_fn(cfg: TransformerConfig, geom: CacheGeometry,
               sp: str = "sp", dp: str = "dp", quantized: bool = False):
    """The prefill shard_map body: (params, kv, x, pages, n_tok) ->
    (out, kv').

    One sequence per call: x (S_bucket, d) is the prompt padded to its
    shape bucket, replicated over BOTH axes (prompt compute is identical
    everywhere — only the cache write is owner-local); pages
    (1, max_pages) is this dp group's row of the page-id table, real ids
    on the owning group and the out-of-range sentinel elsewhere (the
    drop-mode scatter makes non-owners' writes vanish); n_tok is the
    true prompt length.  Returns the full per-position outputs — the
    engine samples from position ``n_tok - 1``, tests compare every one
    against ``model_apply``.

    ``quantized``: K/V land as whole int8 pages — positions at or past
    ``n_tok`` are zeroed before the per-page absmax, and only pages that
    hold at least one prompt token are written (page granularity is
    exactly what makes prefill quantization one reshape + one scatter
    instead of a per-token requantize).
    """
    # S_bucket padded up to whole pages for the page-granular reshape;
    # page count capped at the table width (a bucket can round past it)
    def run(params, kv, x, pages, n_tok):
        kv_k, kv_v = kv["k"], kv["v"]
        k_scale = kv.get("k_scale")
        v_scale = kv.get("v_scale")
        H, Dh = cfg.n_heads, cfg.d_head
        S = x.shape[0]
        pages = pages[0]
        pos = jnp.arange(S)
        page_of = pages[jnp.clip(pos // geom.page_size, 0, pages.shape[0] - 1)]
        # padded positions (pos >= n_tok) write nowhere
        pg = jnp.where(pos < n_tok, page_of, geom.n_pages)
        off = pos % geom.page_size
        if quantized:
            pad = -S % geom.page_size
            n_pg = (S + pad) // geom.page_size
            pg_idx = jnp.arange(n_pg)
            pg_ids = pages[jnp.clip(pg_idx, 0, pages.shape[0] - 1)]
            # only pages holding prompt tokens are written
            pg_write = jnp.where(pg_idx * geom.page_size < n_tok,
                                 pg_ids, geom.n_pages)
            tok_live = (pos < n_tok)[:, None, None]

            def quant_pages(vals):
                live = jnp.where(tok_live, vals, 0.0)
                live = jnp.pad(live, ((0, pad), (0, 0), (0, 0)))
                return quantize_pages(
                    live.reshape(n_pg, geom.page_size, *vals.shape[1:]),
                    kv_k.dtype,
                )
        # causal x true-length mask: padded keys never attend, padded
        # query rows produce garbage that nothing reads
        mask = (pos[:, None] >= pos[None, :]) & (pos[None, :] < n_tok)
        for li, p in enumerate(params["layers"]):
            h = _rms_norm(x, p["ln1"])
            q = _head_slice((h @ p["wq"]).reshape(S, H, Dh), sp, H)
            k = _head_slice((h @ p["wk"]).reshape(S, H, Dh), sp, H)
            v = _head_slice((h @ p["wv"]).reshape(S, H, Dh), sp, H)
            if quantized:
                qk, sk = quant_pages(k)
                qv, sv = quant_pages(v)
                kv_k = kv_k.at[li, pg_write].set(qk, mode="drop")
                kv_v = kv_v.at[li, pg_write].set(qv, mode="drop")
                k_scale = k_scale.at[li, pg_write].set(sk, mode="drop")
                v_scale = v_scale.at[li, pg_write].set(sv, mode="drop")
            else:
                kv_k = kv_k.at[li, pg, off].set(k, mode="drop")
                kv_v = kv_v.at[li, pg, off].set(v, mode="drop")
            s = masked_scores(q, k, mask)                    # (H_loc, S, S)
            pr = masked_softmax(s, mask[None])
            attn = jnp.einsum("hst,thd->shd", pr, v.astype(jnp.float32))
            x = _attn_residual(p, attn.astype(x.dtype), x, cfg, sp)
            x = _moe_residual(p, x, cfg, dp)
        return x, _cache_out(kv_k, kv_v, k_scale, v_scale)

    return run


def build_prefill(mesh: Mesh, cfg: TransformerConfig, geom: CacheGeometry,
                  dp: str = "dp", sp: str = "sp",
                  counter: CompileCounter | None = None,
                  quantized: bool = False):
    """Compiled prefill over ``mesh``: jit'd fn(params, kv, x, pages,
    n_tok) -> (out (S, d), kv'), cache donated.  One compile per prompt
    shape bucket (the engine pads prompts to power-of-two lengths to
    bound the bucket count).  ``quantized`` writes int8 pages; prompt
    COMPUTE stays fp32 either way (prefill attends the just-projected
    values, not the cache), so prefill outputs are dtype-independent."""
    check_serve_mesh(mesh, cfg, dp, sp)
    _check_geometry(cfg, geom)
    body = prefill_fn(cfg, geom, sp=sp, dp=dp, quantized=quantized)
    if counter is not None:
        body = counter.wrap(body)
    pspec = param_spec(cfg, dp)
    kspec = kv_cache_spec(dp, sp, quantized)
    return run_spmd(
        mesh,
        body,
        (pspec, kspec, P(), P(dp), P()),
        (P(), kspec),
        donate_argnums=(1,),
    )
