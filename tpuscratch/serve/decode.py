"""Single-token decode + prompt prefill over the paged KV cache.

The inference twin of ``models/transformer.model_apply``: the same
parameter pytree, the same ``_rms_norm``/projection/MoE math, but
attention reads (and extends) the block-paged cache instead of
recomputing the whole prefix — turning the O(S) per-token forward into
O(1) compute plus an O(S) cache *gather* (``ops.attention.
decode_attention``).  Numerical equivalence to the full forward at every
position is test-gated (tests/test_serve.py) under the no-token-dropped
MoE capacity regime (capacity_factor >= n_experts), since routing is the
one component whose output can depend on which OTHER tokens share the
batch when capacity binds.

Mesh mapping (see serve/kvcache.py for the cache side):

- decode slots shard over **"dp"** (each group decodes its own slots
  against its own page pool);
- heads shard over **"sp"**: every rank projects the full q/k/v from the
  replicated weights, keeps its head slice, attends against its cached
  head slice, and the output projection psums row-blocks of ``wo`` over
  sp — Megatron-style tensor parallelism for the attention sublayer,
  which is what sequence parallelism degenerates to when the sequence
  axis is one token long;
- the MoE FFN runs the training stack's ``expert_parallel_ffn`` over
  "dp" unchanged.

Each builder returns ONE jitted program per batch shape, with a
:class:`CompileCounter` hook that increments on trace — the engine's
zero-recompile-after-warmup assertion hangs off it.  The decode step
donates the cache buffers, so steady-state decode updates pages in place
instead of copying the pool every token.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.models.transformer import (
    TransformerConfig,
    _rms_norm,
    param_spec,
)
from tpuscratch.ops.attention import decode_attention
from tpuscratch.parallel.expert import expert_parallel_ffn
from tpuscratch.parallel.scores import masked_scores, masked_softmax
from tpuscratch.serve.kvcache import CacheGeometry, kv_cache_spec


# promoted to the observability subsystem (recompile detection is not a
# serving-only concern — the trainer's no-retrace coverage uses it too);
# re-exported here so serve-side imports keep working
from tpuscratch.obs.metrics import CompileCounter  # noqa: F401,E402


def check_serve_mesh(mesh: Mesh, cfg: TransformerConfig,
                     dp: str = "dp", sp: str = "sp") -> None:
    """The serve-side mesh preconditions (decode and prefill share them)."""
    if cfg.n_experts % mesh.shape[dp]:
        raise ValueError(
            f"n_experts {cfg.n_experts} not divisible by dp size "
            f"{mesh.shape[dp]}"
        )
    if cfg.n_heads % mesh.shape[sp]:
        raise ValueError(
            f"serving shards heads over sp: n_heads {cfg.n_heads} not "
            f"divisible by sp size {mesh.shape[sp]}"
        )


def _check_geometry(cfg: TransformerConfig, geom: CacheGeometry) -> None:
    """A cache built for a different model fails loudly at build time,
    not as a shape error inside the compiled step."""
    if (geom.n_layers, geom.n_heads, geom.d_head) != (
        cfg.n_layers, cfg.n_heads, cfg.d_head
    ):
        raise ValueError(
            f"cache geometry (layers={geom.n_layers}, heads={geom.n_heads}, "
            f"d_head={geom.d_head}) does not match the model "
            f"(layers={cfg.n_layers}, heads={cfg.n_heads}, "
            f"d_head={cfg.d_head})"
        )


def _head_slice(t, sp: str, n_heads: int):
    """This sp rank's head slice of a (..., n_heads, d_head) projection."""
    n = lax.axis_size(sp)
    h_loc = n_heads // n
    return lax.dynamic_slice_in_dim(
        t, lax.axis_index(sp) * h_loc, h_loc, axis=t.ndim - 2
    )


def _attn_residual(p, attn_loc, x, cfg: TransformerConfig, sp: str):
    """Output projection of this rank's head slice: its row block of the
    replicated ``wo`` + psum over sp assembles the full projection."""
    n = lax.axis_size(sp)
    rows_loc = (cfg.n_heads // n) * cfg.d_head
    wo_rows = lax.dynamic_slice_in_dim(
        p["wo"], lax.axis_index(sp) * rows_loc, rows_loc, axis=0
    )
    flat = attn_loc.reshape(*attn_loc.shape[:-2], rows_loc)
    return x + lax.psum(flat @ wo_rows, sp)


def _moe_residual(p, x, cfg: TransformerConfig, dp: str):
    h = _rms_norm(x, p["ln2"])
    moe, _ = expert_parallel_ffn(
        h, p["gate"], p["w_in"], p["w_out"], dp,
        capacity_factor=cfg.capacity_factor,
    )
    return x + moe


def decode_step_fn(cfg: TransformerConfig, sp: str = "sp", dp: str = "dp"):
    """The decode shard_map body:
    (params, kv, x, page_tables, write_page, write_off, seq_lens)
    -> (out, kv').

    Local shapes: x (B_loc, d) — each slot's current-token vector;
    page_tables (B_loc, max_pages) LOCAL page ids; write_page/write_off
    (B_loc,) — where this token's K/V lands (write_page >= n_pages for
    idle slots: the scatter's drop mode makes them no-ops); seq_lens
    (B_loc,) — cached length INCLUDING this token (0 idles the slot).
    """

    def step(params, kv, x, page_tables, write_page, write_off, seq_lens):
        kv_k, kv_v = kv["k"], kv["v"]
        H, Dh = cfg.n_heads, cfg.d_head
        B = x.shape[0]
        # idle slots must not compete for MoE expert capacity: routing
        # priority is positional, so an idle slot's zero vector ahead of
        # a real token would consume capacity and CHANGE that token's
        # output whenever capacity binds (capacity_factor < n_experts).
        # A stable idle-last permutation keeps the compiled shape fixed
        # while making idle tokens lose every capacity tie; jax sorts
        # are stable, so active slots keep their relative order.
        perm = jnp.argsort((seq_lens == 0).astype(jnp.int32))
        inv = jnp.argsort(perm)
        for li, p in enumerate(params["layers"]):
            h = _rms_norm(x, p["ln1"])
            q = _head_slice((h @ p["wq"]).reshape(B, H, Dh), sp, H)
            k = _head_slice((h @ p["wk"]).reshape(B, H, Dh), sp, H)
            v = _head_slice((h @ p["wv"]).reshape(B, H, Dh), sp, H)
            kv_k = kv_k.at[li, write_page, write_off].set(k, mode="drop")
            kv_v = kv_v.at[li, write_page, write_off].set(v, mode="drop")
            attn = decode_attention(
                q, kv_k[li], kv_v[li], page_tables, seq_lens
            )
            x = _attn_residual(p, attn, x, cfg, sp)
            x = _moe_residual(p, x[perm], cfg, dp)[inv]
        return x, {"k": kv_k, "v": kv_v}

    return step


def build_decode_step(mesh: Mesh, cfg: TransformerConfig,
                      geom: CacheGeometry, dp: str = "dp", sp: str = "sp",
                      counter: CompileCounter | None = None):
    """Compiled decode step over ``mesh``: jit'd
    fn(params, kv, x, page_tables, write_page, write_off, seq_lens) ->
    (out (B, d), kv') with slots sharded P(dp) and the cache donated
    (page pools update in place).  One compile per (B, max_pages)
    bucket; the engine holds B fixed at its slot count, so steady-state
    decode never recompiles (``counter`` proves it)."""
    check_serve_mesh(mesh, cfg, dp, sp)
    _check_geometry(cfg, geom)
    body = decode_step_fn(cfg, sp=sp, dp=dp)
    if counter is not None:
        body = counter.wrap(body)
    pspec = param_spec(cfg, dp)
    kspec = kv_cache_spec(dp, sp)
    return run_spmd(
        mesh,
        body,
        (pspec, kspec, P(dp), P(dp), P(dp), P(dp), P(dp)),
        (P(dp), kspec),
        donate_argnums=(1,),
    )


def prefill_fn(cfg: TransformerConfig, geom: CacheGeometry,
               sp: str = "sp", dp: str = "dp"):
    """The prefill shard_map body: (params, kv, x, pages, n_tok) ->
    (out, kv').

    One sequence per call: x (S_bucket, d) is the prompt padded to its
    shape bucket, replicated over BOTH axes (prompt compute is identical
    everywhere — only the cache write is owner-local); pages
    (1, max_pages) is this dp group's row of the page-id table, real ids
    on the owning group and the out-of-range sentinel elsewhere (the
    drop-mode scatter makes non-owners' writes vanish); n_tok is the
    true prompt length.  Returns the full per-position outputs — the
    engine samples from position ``n_tok - 1``, tests compare every one
    against ``model_apply``.
    """

    def run(params, kv, x, pages, n_tok):
        kv_k, kv_v = kv["k"], kv["v"]
        H, Dh = cfg.n_heads, cfg.d_head
        S = x.shape[0]
        pages = pages[0]
        pos = jnp.arange(S)
        page_of = pages[jnp.clip(pos // geom.page_size, 0, pages.shape[0] - 1)]
        # padded positions (pos >= n_tok) write nowhere
        pg = jnp.where(pos < n_tok, page_of, geom.n_pages)
        off = pos % geom.page_size
        # causal x true-length mask: padded keys never attend, padded
        # query rows produce garbage that nothing reads
        mask = (pos[:, None] >= pos[None, :]) & (pos[None, :] < n_tok)
        for li, p in enumerate(params["layers"]):
            h = _rms_norm(x, p["ln1"])
            q = _head_slice((h @ p["wq"]).reshape(S, H, Dh), sp, H)
            k = _head_slice((h @ p["wk"]).reshape(S, H, Dh), sp, H)
            v = _head_slice((h @ p["wv"]).reshape(S, H, Dh), sp, H)
            kv_k = kv_k.at[li, pg, off].set(k, mode="drop")
            kv_v = kv_v.at[li, pg, off].set(v, mode="drop")
            s = masked_scores(q, k, mask)                    # (H_loc, S, S)
            pr = masked_softmax(s, mask[None])
            attn = jnp.einsum("hst,thd->shd", pr, v.astype(jnp.float32))
            x = _attn_residual(p, attn.astype(x.dtype), x, cfg, sp)
            x = _moe_residual(p, x, cfg, dp)
        return x, {"k": kv_k, "v": kv_v}

    return run


def build_prefill(mesh: Mesh, cfg: TransformerConfig, geom: CacheGeometry,
                  dp: str = "dp", sp: str = "sp",
                  counter: CompileCounter | None = None):
    """Compiled prefill over ``mesh``: jit'd fn(params, kv, x, pages,
    n_tok) -> (out (S, d), kv'), cache donated.  One compile per prompt
    shape bucket (the engine pads prompts to power-of-two lengths to
    bound the bucket count)."""
    check_serve_mesh(mesh, cfg, dp, sp)
    _check_geometry(cfg, geom)
    body = prefill_fn(cfg, geom, sp=sp, dp=dp)
    if counter is not None:
        body = counter.wrap(body)
    pspec = param_spec(cfg, dp)
    kspec = kv_cache_spec(dp, sp)
    return run_spmd(
        mesh,
        body,
        (pspec, kspec, P(), P(dp), P()),
        (P(), kspec),
        donate_argnums=(1,),
    )
