"""Block-paged KV cache: preallocated page pools + a free-list allocator.

The serving-side memory system (PagedAttention / vLLM, SOSP '23, rebuilt
for this framework's mesh conventions): K and V live in preallocated
buffers of shape ``(n_layers, n_pages, page_size, n_heads, d_head)``,
and a sequence's cache is a list of page ids, not a contiguous slab — so
mixed-length sequences pack the pool densely and admission control is
one integer comparison against the free list.

Sharding follows the ``models/transformer.param_spec`` conventions onto
the same (dp, sp) mesh the training step uses:

- **pages shard over "dp"** the way expert leaves shard their expert
  axis: each data-parallel group serves its own decode slots out of its
  own page pool (ids in a page table are LOCAL to the owning group), so
  per-step cache writes touch only the owning shard and the global
  array stays consistent without cross-group traffic;
- **heads shard over "sp"**: at decode there is no sequence axis left to
  shard, so the sequence-parallel ranks hold head slices instead — the
  Ulysses layout (parallel/ulysses.py) applied to the cache.

**Shared pages (prefix caching):** the allocator refcounts every live
page and :class:`PrefixCache` maps full-page-aligned token prefixes to
the live pages holding their K/V, so admissions whose prompts share a
system prefix attach to existing pages (refcount +1) instead of
re-prefilling them; a page is reclaimed only when its last holder
frees it, and the engine copy-on-writes before any write into a page
with more than one holder (serve/engine.py).

The allocator is deliberately HOST-side Python: page grant/release is
scheduler work that happens between compiled steps (the engine's
admission/eviction loop), never inside one — the compiled decode step
only ever sees page *tables*, which are plain int32 arrays.

**Quantized pages (the fp32 / int8 / fp8 dtype ladder):** decode is a
gather of the whole cached prefix per generated token, so cache *bytes*
are the decode roofline.  ``init_kv_cache(..., dtype=...)`` selects the
rung; both quantized rungs store one byte per element with per-page
per-head fp32 scales (``k_scale``/``v_scale``, shape
``(n_layers, pages, n_heads)``) — symmetric absmax scaling,
``value = q * scale``:

- **int8**: ``scale = absmax / 127``, rounded integer grid — uniform
  quantization, error <= scale/2 everywhere, exact at the amax entry;
- **fp8 (e4m3)**: ``scale = absmax / 448``, round-to-nearest float8
  cast — a FLOATING grid: ~6% relative error at every magnitude
  instead of a page-wide absolute step, so small entries on a page
  with one large outlier keep their precision (the regime where int8's
  uniform grid flattens them to zero).  Same bytes as int8 — fp8 is an
  accuracy-per-byte rung, not a further compression rung.

Bytes per token of pool capacity at the two record-config-12
geometries (per layer: ``2 * n_heads * d_head`` payload +
``2 * n_heads * 4 / page_size`` amortized scale; ratio
``1/4 + 1/(page_size * d_head)`` independent of layer count):

===========  ====================  =====================
kv dtype     CPU geometry          TPU geometry
             (1 layer, H2 d16,     (4 layers, H8 d128,
             page 4)               page 16)
===========  ====================  =====================
float32      256 B   (1.000x)      32768 B  (1.000x)
int8         68 B    (0.266x)      8208 B   (0.2505x)
fp8 e4m3     68 B    (0.266x)      8208 B   (0.2505x)
===========  ====================  =====================

Page residency rises ~4x on either quantized rung and the decode
gather moves a quarter of the wire/HBM bytes.  Scales sit OUTSIDE the
page payload so the gather stays a dense 1-byte copy; dequantization
happens after the gather — folded into the attention contractions on
the dense path, in VMEM inside the fused Pallas kernel
(``ops.attention.paged_attention``).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

#: symmetric int8 range: q in [-127, 127], value = q * scale
INT8_QMAX = 127.0

#: fp8 e4m3fn finite max: q in [-448, 448], value = q * scale (the
#: "fn" variant has no inf — 448 is the whole representable range)
FP8_QMAX = 448.0

#: the quantized rungs of the KV dtype ladder and their absmax targets
#: (fp32 is the unquantized rung: no scale planes, no entry here)
QUANT_KV_DTYPES = {
    jnp.dtype(jnp.int8): INT8_QMAX,
    jnp.dtype(jnp.float8_e4m3fn): FP8_QMAX,
}

#: absmax floor — an all-zero page quantizes with this scale instead of
#: dividing by zero (dequantizes back to exact zeros either way)
_SCALE_FLOOR = 1e-30


def is_quantized_kv_dtype(dtype) -> bool:
    """True for the 1-byte-per-element rungs that carry scale planes."""
    return jnp.dtype(dtype) in QUANT_KV_DTYPES


@dataclasses.dataclass(frozen=True)
class CacheGeometry:
    """Static shape of one data-parallel group's page pool."""

    n_layers: int
    n_pages: int          # pages per dp group
    page_size: int        # tokens per page
    n_heads: int          # GLOBAL head count (sharded over sp)
    d_head: int

    def __post_init__(self):
        for name in ("n_layers", "n_pages", "page_size", "n_heads", "d_head"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1, got {getattr(self, name)}")

    @property
    def max_tokens(self) -> int:
        """Token capacity of one group's pool."""
        return self.n_pages * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens``."""
        return -(-n_tokens // self.page_size)


def init_kv_cache(geom: CacheGeometry, dp_size: int = 1,
                  dtype=jnp.float32) -> dict:
    """The global cache pytree: ``{"k", "v"}`` buffers of shape
    ``(n_layers, dp_size * n_pages, page_size, n_heads, d_head)`` — the
    pages axis carries every group's pool (sharded over dp it splits back
    to ``n_pages`` per group), heads global (sharded over sp).

    A quantized dtype (``jnp.int8`` or ``jnp.float8_e4m3fn``) adds the
    per-page per-head quantization scales: ``{"k_scale", "v_scale"}``
    fp32 buffers of shape ``(n_layers, dp_size * n_pages, n_heads)``."""
    if jnp.dtype(dtype) != jnp.dtype(jnp.float32) and not (
        is_quantized_kv_dtype(dtype)
    ):
        raise ValueError(
            f"kv cache dtype {jnp.dtype(dtype)} not in the ladder "
            f"(float32, int8, float8_e4m3fn)"
        )
    shape = (geom.n_layers, dp_size * geom.n_pages, geom.page_size,
             geom.n_heads, geom.d_head)
    cache = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if is_quantized_kv_dtype(dtype):
        sshape = shape[:2] + (geom.n_heads,)
        cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
        cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
    return cache


def kv_cache_spec(dp: str = "dp", sp: str = "sp",
                  quantized: bool = False) -> dict:
    """PartitionSpec pytree for :func:`init_kv_cache`'s output."""
    s = P(None, dp, None, sp, None)
    out = {"k": s, "v": s}
    if quantized:
        out["k_scale"] = P(None, dp, sp)
        out["v_scale"] = P(None, dp, sp)
    return out


def quantize_pages(x: jnp.ndarray,
                   dtype=jnp.int8) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric absmax quantization of page-shaped values onto a rung
    of the KV dtype ladder: x ``(..., page_size, n_heads, d_head)``
    fp32 -> (q in ``dtype`` same shape, scale ``(..., n_heads)`` fp32).
    The scale is per PAGE per HEAD — one amax over the page's tokens
    and the head dim — so a page gather drags ``n_heads`` floats of
    metadata, not a per-token vector.

    ``dtype=jnp.int8``: rounded integer grid, exactly invertible at
    the amax entry (``round(127) * amax/127``), elsewhere within
    ``scale/2``.  ``dtype=jnp.float8_e4m3fn``: round-to-nearest float8
    cast of ``x / scale`` with the scale targeting the e4m3 finite max
    (448) — relative error ~2^-4 at any magnitude (3 mantissa bits),
    absolute error below ``scale * 2^-10`` in the subnormal tail; the
    explicit clip keeps division slop at the amax entry from rounding
    past 448 (e4m3fn has no inf — the overflow would land on NaN, not
    saturate)."""
    dtype = jnp.dtype(dtype)
    if dtype not in QUANT_KV_DTYPES:
        raise ValueError(
            f"quantize_pages dtype {dtype} not a quantized rung "
            f"(int8, float8_e4m3fn)"
        )
    qmax = QUANT_KV_DTYPES[dtype]
    amax = jnp.max(jnp.abs(x), axis=(-3, -1))
    scale = jnp.maximum(amax, _SCALE_FLOOR) / qmax
    y = x / scale[..., None, :, None]
    if dtype == jnp.dtype(jnp.int8):
        y = jnp.round(y)
    q = jnp.clip(y, -qmax, qmax).astype(dtype)
    return q, scale


def dequantize_pages(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`quantize_pages`: int8/fp8 pages x
    ``(..., n_heads)`` scales -> fp32 values."""
    return q.astype(jnp.float32) * scale[..., None, :, None]


class PageAllocator:
    """LIFO free-list over one group's ``n_pages`` page ids, with
    per-page REFCOUNTS so live pages can be shared across requests
    (PagedAttention block sharing, Kwon et al. SOSP '23).

    Invariants (test-gated in tests/test_serve.py):
    - every id handed out is in ``[0, n_pages)`` and unique among live ids;
    - :meth:`alloc` is all-or-nothing — a request it cannot fully satisfy
      grants nothing and returns None (no partial reservations to unwind);
    - :meth:`alloc` grants refcount 1; :meth:`share` adds a holder to an
      already-live page; :meth:`free` drops ONE holder, and a page
      returns to the free list only when its LAST holder frees it — so
      eviction can never reclaim a page another request still reads;
    - :meth:`free`/:meth:`share` of an id that is not currently live
      (double free, or a foreign id) raise instead of corrupting state;
    - ``n_free`` counts UNIQUE reclaimable pages (sharing a page does
      not consume free-list capacity): after every holder of every live
      page frees, ``n_free`` returns to ``n_pages``.

    LIFO keeps recently-freed (cache-warm, recently-DMA'd) pages hot —
    the same reuse policy as the native host pool's size-class lists
    (native/src/host_pool.cpp).
    """

    def __init__(self, n_pages: int):
        if n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {n_pages}")
        self.n_pages = n_pages
        self._free = list(range(n_pages - 1, -1, -1))  # pop() hands out 0 first
        self._refs: dict[int, int] = {}                # live page -> holders

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_live(self) -> int:
        """UNIQUE live pages (a page shared k ways counts once) — the
        quantity the engine's free-page watermark law is stated over."""
        return len(self._refs)

    def refcount(self, page: int) -> int:
        """Current holder count (0 for a free page) — the engine's
        copy-on-write trigger reads this before any in-place write."""
        return self._refs.get(page, 0)

    def alloc(self, n: int = 1) -> Optional[list[int]]:
        """Grant ``n`` pages at refcount 1, or None (and grant nothing)
        if fewer are free."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def share(self, pages: Iterable[int]) -> None:
        """Add one holder to each LIVE page — the prefix-cache hit path.
        Rejects non-live ids: sharing a freed page would resurrect it."""
        pages = list(pages)
        for p in pages:
            if p not in self._refs:
                raise ValueError(
                    f"page {p} is not live (cannot share a freed page; "
                    f"{len(self._refs)} live of {self.n_pages})"
                )
        for p in pages:
            self._refs[p] += 1

    def free(self, pages: Iterable[int]) -> list[int]:
        """Drop one holder per page; pages whose LAST holder left return
        to the free list and are listed in the return value (the engine
        drops exactly those from its prefix trie).  Rejects ids not
        currently live."""
        released = []
        for p in pages:
            if p not in self._refs:
                raise ValueError(
                    f"page {p} is not live (double free or foreign id; "
                    f"{len(self._refs)} live of {self.n_pages})"
                )
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)
                released.append(p)
        return released


class PrefixCache:
    """Token-block trie over one group's LIVE pages: full-page-aligned
    prompt prefixes -> the page id holding that block's K/V.

    The cross-request sharing index (PagedAttention prefix caching):
    a key is the WHOLE token prefix up to a page boundary (tuple of
    ``i * page_size`` token ids), so two prompts match a page only when
    everything before it is identical too — the residual stream at a
    position depends on the entire prefix, so K/V values are reusable
    exactly when the full prefix matches (this model has no positional
    encoding beyond the causal mask, and cached projections depend only
    on the prefix).

    Entries index pages whose holders are tracked by
    :class:`PageAllocator` refcounts — the trie itself holds NO
    reference: a mapping dies with its page (``drop`` on the
    allocator's released list), so only pages some live request still
    holds are ever matched, and the watermark law keeps counting unique
    live pages.  A key tracks ALTERNATE physical copies: two identical
    prompts prefilled in the same tick each register their own pages
    (neither could share — sharing needs a COMPLETED prefill), matches
    land on the oldest live copy, and when that copy's owner dies the
    next alternate takes over instead of the whole chain vanishing
    while an equivalent live copy exists.
    """

    def __init__(self, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self._map: dict[tuple, list[int]] = {}  # prefix -> live copies
        self._rev: dict[int, set[tuple]] = {}   # page id -> its keys

    @property
    def n_blocks(self) -> int:
        return len(self._map)

    def match(self, prompt: Iterable[int]) -> list[int]:
        """Page ids of the LONGEST cached full-page-aligned prefix of
        ``prompt`` (possibly empty).  The chain walks block by block, so
        a match is always a contiguous prefix."""
        prompt = tuple(prompt)
        pages = []
        for i in range(self.page_size, len(prompt) + 1, self.page_size):
            alts = self._map.get(prompt[:i])
            if not alts:
                break
            pages.append(alts[0])
        return pages

    def insert(self, prompt: Iterable[int], pages: Iterable[int]) -> None:
        """Register ``prompt``'s full-page blocks against the pages that
        hold them (``pages`` in sequence order, one per full block;
        extra tail entries ignored).  A key that already indexes other
        copies gains an alternate; matches keep landing on the oldest."""
        prompt, pages = tuple(prompt), list(pages)
        for blk, page in zip(range(len(prompt) // self.page_size), pages):
            key = prompt[: (blk + 1) * self.page_size]
            alts = self._map.setdefault(key, [])
            if page not in alts:
                alts.append(page)
                self._rev.setdefault(page, set()).add(key)

    def drop(self, pages: Iterable[int]) -> None:
        """Forget every mapping onto ``pages`` — called with the
        allocator's released list, so dead pages cannot be matched;
        keys with surviving alternate copies stay matchable."""
        for p in pages:
            for key in self._rev.pop(p, ()):
                alts = self._map.get(key)
                if alts is None:
                    continue
                if p in alts:
                    alts.remove(p)
                if not alts:
                    del self._map[key]

    def clear(self) -> None:
        """Forget everything — the engine's cache-recovery path (a reset
        pool holds no valid K/V, so no prefix may be matched)."""
        self._map.clear()
        self._rev.clear()
