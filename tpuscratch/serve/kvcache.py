"""Block-paged KV cache: preallocated page pools + a free-list allocator.

The serving-side memory system (PagedAttention / vLLM, SOSP '23, rebuilt
for this framework's mesh conventions): K and V live in preallocated
buffers of shape ``(n_layers, n_pages, page_size, n_heads, d_head)``,
and a sequence's cache is a list of page ids, not a contiguous slab — so
mixed-length sequences pack the pool densely and admission control is
one integer comparison against the free list.

Sharding follows the ``models/transformer.param_spec`` conventions onto
the same (dp, sp) mesh the training step uses:

- **pages shard over "dp"** the way expert leaves shard their expert
  axis: each data-parallel group serves its own decode slots out of its
  own page pool (ids in a page table are LOCAL to the owning group), so
  per-step cache writes touch only the owning shard and the global
  array stays consistent without cross-group traffic;
- **heads shard over "sp"**: at decode there is no sequence axis left to
  shard, so the sequence-parallel ranks hold head slices instead — the
  Ulysses layout (parallel/ulysses.py) applied to the cache.

The allocator is deliberately HOST-side Python: page grant/release is
scheduler work that happens between compiled steps (the engine's
admission/eviction loop), never inside one — the compiled decode step
only ever sees page *tables*, which are plain int32 arrays.

**Quantized pages (int8):** decode is a gather of the whole cached
prefix per generated token, so cache *bytes* are the decode roofline.
``init_kv_cache(..., dtype=jnp.int8)`` stores K/V pages as int8 with
per-page per-head fp32 scales (``k_scale``/``v_scale``, shape
``(n_layers, pages, n_heads)``) — symmetric absmax quantization,
``value = q * scale`` with ``scale = absmax / 127``.  Cache bytes per
token drop ~4x (one int8 byte vs four, plus ``2 * 4 / page_size`` bytes
of amortized scale), page residency rises accordingly, and the decode
gather moves a quarter of the wire/HBM bytes.  Scales sit OUTSIDE the
page payload so the gather stays a dense int8 copy; dequantization
happens after the gather, inside ``ops.attention.decode_attention``.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Optional

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

#: symmetric int8 range: q in [-127, 127], value = q * scale
INT8_QMAX = 127.0

#: absmax floor — an all-zero page quantizes with this scale instead of
#: dividing by zero (dequantizes back to exact zeros either way)
_SCALE_FLOOR = 1e-30


@dataclasses.dataclass(frozen=True)
class CacheGeometry:
    """Static shape of one data-parallel group's page pool."""

    n_layers: int
    n_pages: int          # pages per dp group
    page_size: int        # tokens per page
    n_heads: int          # GLOBAL head count (sharded over sp)
    d_head: int

    def __post_init__(self):
        for name in ("n_layers", "n_pages", "page_size", "n_heads", "d_head"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1, got {getattr(self, name)}")

    @property
    def max_tokens(self) -> int:
        """Token capacity of one group's pool."""
        return self.n_pages * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens``."""
        return -(-n_tokens // self.page_size)


def init_kv_cache(geom: CacheGeometry, dp_size: int = 1,
                  dtype=jnp.float32) -> dict:
    """The global cache pytree: ``{"k", "v"}`` buffers of shape
    ``(n_layers, dp_size * n_pages, page_size, n_heads, d_head)`` — the
    pages axis carries every group's pool (sharded over dp it splits back
    to ``n_pages`` per group), heads global (sharded over sp).

    ``dtype=jnp.int8`` adds the per-page per-head quantization scales:
    ``{"k_scale", "v_scale"}`` fp32 buffers of shape
    ``(n_layers, dp_size * n_pages, n_heads)``."""
    shape = (geom.n_layers, dp_size * geom.n_pages, geom.page_size,
             geom.n_heads, geom.d_head)
    cache = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if dtype == jnp.int8:
        sshape = shape[:2] + (geom.n_heads,)
        cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
        cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
    return cache


def kv_cache_spec(dp: str = "dp", sp: str = "sp",
                  quantized: bool = False) -> dict:
    """PartitionSpec pytree for :func:`init_kv_cache`'s output."""
    s = P(None, dp, None, sp, None)
    out = {"k": s, "v": s}
    if quantized:
        out["k_scale"] = P(None, dp, sp)
        out["v_scale"] = P(None, dp, sp)
    return out


def quantize_pages(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric absmax int8 quantization of page-shaped values:
    x ``(..., page_size, n_heads, d_head)`` fp32 ->
    (q int8 same shape, scale ``(..., n_heads)`` fp32).  The scale is
    per PAGE per HEAD — one amax over the page's tokens and the head
    dim — so a page gather drags ``n_heads`` floats of metadata, not a
    per-token vector.  Exactly invertible at the amax entry
    (``round(127) * amax/127``), elsewhere within ``scale/2``."""
    amax = jnp.max(jnp.abs(x), axis=(-3, -1))
    scale = jnp.maximum(amax, _SCALE_FLOOR) / INT8_QMAX
    q = jnp.round(x / scale[..., None, :, None])
    q = jnp.clip(q, -INT8_QMAX, INT8_QMAX).astype(jnp.int8)
    return q, scale


def dequantize_pages(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`quantize_pages`: int8 pages x ``(..., n_heads)``
    scales -> fp32 values."""
    return q.astype(jnp.float32) * scale[..., None, :, None]


class PageAllocator:
    """LIFO free-list over one group's ``n_pages`` page ids.

    Invariants (test-gated in tests/test_serve.py):
    - every id handed out is in ``[0, n_pages)`` and unique among live ids;
    - :meth:`alloc` is all-or-nothing — a request it cannot fully satisfy
      grants nothing and returns None (no partial reservations to unwind);
    - :meth:`free` of an id that is not currently live (double free, or a
      foreign id) raises instead of corrupting the list;
    - after every live id is freed, ``n_free`` returns to ``n_pages``.

    LIFO keeps recently-freed (cache-warm, recently-DMA'd) pages hot —
    the same reuse policy as the native host pool's size-class lists
    (native/src/host_pool.cpp).
    """

    def __init__(self, n_pages: int):
        if n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {n_pages}")
        self.n_pages = n_pages
        self._free = list(range(n_pages - 1, -1, -1))  # pop() hands out 0 first
        self._live: set[int] = set()

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_live(self) -> int:
        return len(self._live)

    def alloc(self, n: int = 1) -> Optional[list[int]]:
        """Grant ``n`` pages, or None (and grant nothing) if fewer are free."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._live.update(pages)
        return pages

    def free(self, pages: Iterable[int]) -> None:
        """Return pages to the free list; rejects ids not currently live."""
        for p in pages:
            if p not in self._live:
                raise ValueError(
                    f"page {p} is not live (double free or foreign id; "
                    f"{len(self._live)} live of {self.n_pages})"
                )
            self._live.discard(p)
            self._free.append(p)
