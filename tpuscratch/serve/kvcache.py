"""Block-paged KV cache: preallocated page pools + a free-list allocator.

The serving-side memory system (PagedAttention / vLLM, SOSP '23, rebuilt
for this framework's mesh conventions): K and V live in preallocated
buffers of shape ``(n_layers, n_pages, page_size, n_heads, d_head)``,
and a sequence's cache is a list of page ids, not a contiguous slab — so
mixed-length sequences pack the pool densely and admission control is
one integer comparison against the free list.

Sharding follows the ``models/transformer.param_spec`` conventions onto
the same (dp, sp) mesh the training step uses:

- **pages shard over "dp"** the way expert leaves shard their expert
  axis: each data-parallel group serves its own decode slots out of its
  own page pool (ids in a page table are LOCAL to the owning group), so
  per-step cache writes touch only the owning shard and the global
  array stays consistent without cross-group traffic;
- **heads shard over "sp"**: at decode there is no sequence axis left to
  shard, so the sequence-parallel ranks hold head slices instead — the
  Ulysses layout (parallel/ulysses.py) applied to the cache.

**Shared pages (prefix caching):** the allocator refcounts every live
page and :class:`PrefixCache` maps full-page-aligned token prefixes to
the live pages holding their K/V, so admissions whose prompts share a
system prefix attach to existing pages (refcount +1) instead of
re-prefilling them; a page is reclaimed only when its last holder
frees it, and the engine copy-on-writes before any write into a page
with more than one holder (serve/engine.py).

The allocator is deliberately HOST-side Python: page grant/release is
scheduler work that happens between compiled steps (the engine's
admission/eviction loop), never inside one — the compiled decode step
only ever sees page *tables*, which are plain int32 arrays.

**Quantized pages (the fp32 / int8 / fp8 dtype ladder):** decode is a
gather of the whole cached prefix per generated token, so cache *bytes*
are the decode roofline.  ``init_kv_cache(..., dtype=...)`` selects the
rung; both quantized rungs store one byte per element with per-page
per-head fp32 scales (``k_scale``/``v_scale``, shape
``(n_layers, pages, n_heads)``) — symmetric absmax scaling,
``value = q * scale``:

- **int8**: ``scale = absmax / 127``, rounded integer grid — uniform
  quantization, error <= scale/2 everywhere, exact at the amax entry;
- **fp8 (e4m3)**: ``scale = absmax / 448``, round-to-nearest float8
  cast — a FLOATING grid: ~6% relative error at every magnitude
  instead of a page-wide absolute step, so small entries on a page
  with one large outlier keep their precision (the regime where int8's
  uniform grid flattens them to zero).  Same bytes as int8 — fp8 is an
  accuracy-per-byte rung, not a further compression rung.

Bytes per token of pool capacity at the two record-config-12
geometries (per layer: ``2 * n_heads * d_head`` payload +
``2 * n_heads * 4 / page_size`` amortized scale; ratio
``1/4 + 1/(page_size * d_head)`` independent of layer count):

===========  ====================  =====================
kv dtype     CPU geometry          TPU geometry
             (1 layer, H2 d16,     (4 layers, H8 d128,
             page 4)               page 16)
===========  ====================  =====================
float32      256 B   (1.000x)      32768 B  (1.000x)
int8         68 B    (0.266x)      8208 B   (0.2505x)
fp8 e4m3     68 B    (0.266x)      8208 B   (0.2505x)
===========  ====================  =====================

Page residency rises ~4x on either quantized rung and the decode
gather moves a quarter of the wire/HBM bytes.  Scales sit OUTSIDE the
page payload so the gather stays a dense 1-byte copy; dequantization
happens after the gather — folded into the attention contractions on
the dense path, in VMEM inside the fused Pallas kernel
(``ops.attention.paged_attention``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable, Optional

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

#: symmetric int8 range: q in [-127, 127], value = q * scale
INT8_QMAX = 127.0

#: fp8 e4m3fn finite max: q in [-448, 448], value = q * scale (the
#: "fn" variant has no inf — 448 is the whole representable range)
FP8_QMAX = 448.0

#: the quantized rungs of the KV dtype ladder and their absmax targets
#: (fp32 is the unquantized rung: no scale planes, no entry here)
QUANT_KV_DTYPES = {
    jnp.dtype(jnp.int8): INT8_QMAX,
    jnp.dtype(jnp.float8_e4m3fn): FP8_QMAX,
}

#: absmax floor — an all-zero page quantizes with this scale instead of
#: dividing by zero (dequantizes back to exact zeros either way)
_SCALE_FLOOR = 1e-30


def is_quantized_kv_dtype(dtype) -> bool:
    """True for the 1-byte-per-element rungs that carry scale planes."""
    return jnp.dtype(dtype) in QUANT_KV_DTYPES


@dataclasses.dataclass(frozen=True)
class CacheGeometry:
    """Static shape of one data-parallel group's page pool."""

    n_layers: int
    n_pages: int          # pages per dp group
    page_size: int        # tokens per page
    n_heads: int          # GLOBAL head count (sharded over sp)
    d_head: int

    def __post_init__(self):
        for name in ("n_layers", "n_pages", "page_size", "n_heads", "d_head"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1, got {getattr(self, name)}")

    @property
    def max_tokens(self) -> int:
        """Token capacity of one group's pool."""
        return self.n_pages * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens``."""
        return -(-n_tokens // self.page_size)


def init_kv_cache(geom: CacheGeometry, dp_size: int = 1,
                  dtype=jnp.float32) -> dict:
    """The global cache pytree: ``{"k", "v"}`` buffers of shape
    ``(n_layers, dp_size * n_pages, page_size, n_heads, d_head)`` — the
    pages axis carries every group's pool (sharded over dp it splits back
    to ``n_pages`` per group), heads global (sharded over sp).

    A quantized dtype (``jnp.int8`` or ``jnp.float8_e4m3fn``) adds the
    per-page per-head quantization scales: ``{"k_scale", "v_scale"}``
    fp32 buffers of shape ``(n_layers, dp_size * n_pages, n_heads)``."""
    if jnp.dtype(dtype) != jnp.dtype(jnp.float32) and not (
        is_quantized_kv_dtype(dtype)
    ):
        raise ValueError(
            f"kv cache dtype {jnp.dtype(dtype)} not in the ladder "
            f"(float32, int8, float8_e4m3fn)"
        )
    shape = (geom.n_layers, dp_size * geom.n_pages, geom.page_size,
             geom.n_heads, geom.d_head)
    cache = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if is_quantized_kv_dtype(dtype):
        sshape = shape[:2] + (geom.n_heads,)
        cache["k_scale"] = jnp.zeros(sshape, jnp.float32)
        cache["v_scale"] = jnp.zeros(sshape, jnp.float32)
    return cache


def kv_cache_spec(dp: str = "dp", sp: str = "sp",
                  quantized: bool = False) -> dict:
    """PartitionSpec pytree for :func:`init_kv_cache`'s output."""
    s = P(None, dp, None, sp, None)
    out = {"k": s, "v": s}
    if quantized:
        out["k_scale"] = P(None, dp, sp)
        out["v_scale"] = P(None, dp, sp)
    return out


def quantize_pages(x: jnp.ndarray,
                   dtype=jnp.int8) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric absmax quantization of page-shaped values onto a rung
    of the KV dtype ladder: x ``(..., page_size, n_heads, d_head)``
    fp32 -> (q in ``dtype`` same shape, scale ``(..., n_heads)`` fp32).
    The scale is per PAGE per HEAD — one amax over the page's tokens
    and the head dim — so a page gather drags ``n_heads`` floats of
    metadata, not a per-token vector.

    ``dtype=jnp.int8``: rounded integer grid, exactly invertible at
    the amax entry (``round(127) * amax/127``), elsewhere within
    ``scale/2``.  ``dtype=jnp.float8_e4m3fn``: round-to-nearest float8
    cast of ``x / scale`` with the scale targeting the e4m3 finite max
    (448) — relative error ~2^-4 at any magnitude (3 mantissa bits),
    absolute error below ``scale * 2^-10`` in the subnormal tail; the
    explicit clip keeps division slop at the amax entry from rounding
    past 448 (e4m3fn has no inf — the overflow would land on NaN, not
    saturate)."""
    dtype = jnp.dtype(dtype)
    if dtype not in QUANT_KV_DTYPES:
        raise ValueError(
            f"quantize_pages dtype {dtype} not a quantized rung "
            f"(int8, float8_e4m3fn)"
        )
    qmax = QUANT_KV_DTYPES[dtype]
    amax = jnp.max(jnp.abs(x), axis=(-3, -1))
    scale = jnp.maximum(amax, _SCALE_FLOOR) / qmax
    y = x / scale[..., None, :, None]
    if dtype == jnp.dtype(jnp.int8):
        y = jnp.round(y)
    q = jnp.clip(y, -qmax, qmax).astype(dtype)
    return q, scale


def dequantize_pages(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`quantize_pages`: int8/fp8 pages x
    ``(..., n_heads)`` scales -> fp32 values."""
    return q.astype(jnp.float32) * scale[..., None, :, None]


class PageAllocator:
    """LIFO free-list over one group's ``n_pages`` page ids, with
    per-page REFCOUNTS so live pages can be shared across requests
    (PagedAttention block sharing, Kwon et al. SOSP '23).

    Invariants (test-gated in tests/test_serve.py):
    - every id handed out is in ``[0, n_pages)`` and unique among live ids;
    - :meth:`alloc` is all-or-nothing — a request it cannot fully satisfy
      grants nothing and returns None (no partial reservations to unwind);
    - :meth:`alloc` grants refcount 1; :meth:`share` adds a holder to an
      already-live page; :meth:`free` drops ONE holder, and a page
      returns to the free list only when its LAST holder frees it — so
      eviction can never reclaim a page another request still reads;
    - :meth:`free`/:meth:`share` of an id that is not currently live
      (double free, or a foreign id) raise instead of corrupting state;
    - ``n_free`` counts UNIQUE reclaimable pages (sharing a page does
      not consume free-list capacity): after every holder of every live
      page frees, ``n_free`` returns to ``n_pages``.

    LIFO keeps recently-freed (cache-warm, recently-DMA'd) pages hot —
    the same reuse policy as the native host pool's size-class lists
    (native/src/host_pool.cpp).
    """

    def __init__(self, n_pages: int):
        if n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {n_pages}")
        self.n_pages = n_pages
        self._free = list(range(n_pages - 1, -1, -1))  # pop() hands out 0 first
        self._refs: dict[int, int] = {}                # live page -> holders

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_live(self) -> int:
        """UNIQUE live pages (a page shared k ways counts once) — the
        quantity the engine's free-page watermark law is stated over."""
        return len(self._refs)

    def refcount(self, page: int) -> int:
        """Current holder count (0 for a free page) — the engine's
        copy-on-write trigger reads this before any in-place write."""
        return self._refs.get(page, 0)

    def alloc(self, n: int = 1) -> Optional[list[int]]:
        """Grant ``n`` pages at refcount 1, or None (and grant nothing)
        if fewer are free."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def share(self, pages: Iterable[int]) -> None:
        """Add one holder to each LIVE page — the prefix-cache hit path.
        Rejects non-live ids: sharing a freed page would resurrect it."""
        pages = list(pages)
        for p in pages:
            if p not in self._refs:
                raise ValueError(
                    f"page {p} is not live (cannot share a freed page; "
                    f"{len(self._refs)} live of {self.n_pages})"
                )
        for p in pages:
            self._refs[p] += 1

    def free(self, pages: Iterable[int]) -> list[int]:
        """Drop one holder per page; pages whose LAST holder left return
        to the free list and are listed in the return value (the engine
        drops exactly those from its prefix trie).  Rejects ids not
        currently live."""
        released = []
        for p in pages:
            if p not in self._refs:
                raise ValueError(
                    f"page {p} is not live (double free or foreign id; "
                    f"{len(self._refs)} live of {self.n_pages})"
                )
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)
                released.append(p)
        return released


class PrefixCache:
    """Token-block trie over one group's LIVE pages: full-page-aligned
    prompt prefixes -> the page id holding that block's K/V.

    The cross-request sharing index (PagedAttention prefix caching):
    a key is the WHOLE token prefix up to a page boundary (tuple of
    ``i * page_size`` token ids), so two prompts match a page only when
    everything before it is identical too — the residual stream at a
    position depends on the entire prefix, so K/V values are reusable
    exactly when the full prefix matches (this model has no positional
    encoding beyond the causal mask, and cached projections depend only
    on the prefix).

    Entries index pages whose holders are tracked by
    :class:`PageAllocator` refcounts — the trie itself holds NO
    reference: a mapping dies with its page (``drop`` on the
    allocator's released list), so only pages some live request still
    holds are ever matched, and the watermark law keeps counting unique
    live pages.  A key tracks ALTERNATE physical copies: two identical
    prompts prefilled in the same tick each register their own pages
    (neither could share — sharing needs a COMPLETED prefill), matches
    land on the oldest live copy, and when that copy's owner dies the
    next alternate takes over instead of the whole chain vanishing
    while an equivalent live copy exists.

    **Sub-page (token-granular) continuations** (ISSUE 14, the PR-8
    remainder): beside the full-block map, every registered block —
    full or partial-tail — is ALSO indexed as a continuation of the
    aligned prefix BEFORE it: key ``prompt[:b * page_size]`` -> list of
    ``(block_tokens, page)``.  :meth:`match_tail` then extends an
    aligned match past its last page boundary: the longest common
    prefix between a registered block's tokens and the request's
    remaining prompt names how many tokens of that donor page are
    valid K/V for the request (K/V at position ``j`` depends only on
    tokens ``[0, j]``, which agree by construction).  The engine
    copy-on-writes the donor page into the admission's own boundary
    page at the token frontier, so affinity/sharing wins are no longer
    quantized to ``page_size``.
    """

    def __init__(self, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self._map: dict[tuple, list[int]] = {}  # prefix -> live copies
        self._rev: dict[int, set[tuple]] = {}   # page id -> its keys
        # sub-page continuation index: aligned key -> [(block, page)]
        self._tails: dict[tuple, list[tuple[tuple, int]]] = {}
        self._rev_tails: dict[int, set[tuple]] = {}  # page -> tail keys

    @property
    def n_blocks(self) -> int:
        return len(self._map)

    def match(self, prompt: Iterable[int],
              prefer: Optional[Callable[[int], bool]] = None) -> list[int]:
        """Page ids of the LONGEST cached full-page-aligned prefix of
        ``prompt`` (possibly empty).  The chain walks block by block, so
        a match is always a contiguous prefix.

        ``prefer`` biases alternate selection: when given, the first
        alternate satisfying it wins, falling back to the oldest copy —
        the tiered engine passes "is live" so a chain with both a live
        holder and a host-parked copy attaches to the live one (sharing
        a live page is free; restoring a parked one pays an H2D copy)."""
        prompt = tuple(prompt)
        pages = []
        for i in range(self.page_size, len(prompt) + 1, self.page_size):
            alts = self._map.get(prompt[:i])
            if not alts:
                break
            if prefer is None:
                pages.append(alts[0])
            else:
                pages.append(next((p for p in alts if prefer(p)), alts[0]))
        return pages

    def insert(self, prompt: Iterable[int], pages: Iterable[int]) -> None:
        """Register ``prompt``'s full-page blocks against the pages that
        hold them (``pages`` in sequence order, one per full block, plus
        the partial-tail page when the prompt ends mid-page; further
        entries ignored).  A key that already indexes other copies gains
        an alternate; matches keep landing on the oldest.  Every block —
        the partial tail included — is also registered as a sub-page
        CONTINUATION of the aligned prefix before it (see
        :meth:`match_tail`)."""
        prompt, pages = tuple(prompt), list(pages)
        ps = self.page_size
        for blk, page in zip(range(len(prompt) // ps), pages):
            key = prompt[: (blk + 1) * ps]
            alts = self._map.setdefault(key, [])
            if page not in alts:
                alts.append(page)
                self._rev.setdefault(page, set()).add(key)
            self._insert_tail(prompt[: blk * ps], key[blk * ps:], page)
        nb, rem = divmod(len(prompt), ps)
        if rem and nb < len(pages):
            # the partial last block: matchable only token-granularly
            self._insert_tail(prompt[: nb * ps], prompt[nb * ps:],
                              pages[nb])

    def _insert_tail(self, key: tuple, block: tuple, page: int) -> None:
        alts = self._tails.setdefault(key, [])
        if (block, page) not in alts:
            alts.append((block, page))
            self._rev_tails.setdefault(page, set()).add(key)

    def match_tail(self, prompt: Iterable[int], matched_pages: int,
                   prefer: Optional[Callable[[int], bool]] = None,
                   ) -> tuple[Optional[int], int]:
        """``(page, n_tokens)`` of the best sub-page continuation past
        an aligned match of ``matched_pages`` full pages: the donor
        page whose registered block shares the longest (>= 1) token
        prefix with the prompt's remainder.  ``prefer`` filters donors
        (the engine passes "is live" — a sub-page donor is COPIED, not
        refcounted, so it must be readable right now); ``(None, 0)``
        when nothing continues the match."""
        prompt = tuple(prompt)
        key = prompt[: matched_pages * self.page_size]
        rest = prompt[matched_pages * self.page_size:]
        best_page, best_n = None, 0
        for block, page in self._tails.get(key, ()):
            if prefer is not None and not prefer(page):
                continue
            n = 0
            for a, b in zip(block, rest):
                if a != b:
                    break
                n += 1
            if n > best_n:
                best_page, best_n = page, n
        return best_page, best_n

    def registered(self, page: int) -> bool:
        """True when ``page`` indexes at least one prefix block — the
        tiered engine's park predicate: only trie-registered pages are
        worth retaining in the host tier after their last holder."""
        return page in self._rev

    def drop(self, pages: Iterable[int]) -> None:
        """Forget every mapping onto ``pages`` — called with the
        allocator's released list, so dead pages cannot be matched;
        keys with surviving alternate copies stay matchable.  Sub-page
        continuation entries die with their page the same way."""
        for p in pages:
            for key in self._rev.pop(p, ()):
                alts = self._map.get(key)
                if alts is None:
                    continue
                if p in alts:
                    alts.remove(p)
                if not alts:
                    del self._map[key]
            for key in self._rev_tails.pop(p, ()):
                alts = self._tails.get(key)
                if alts is None:
                    continue
                alts[:] = [bp for bp in alts if bp[1] != p]
                if not alts:
                    del self._tails[key]

    def clear(self) -> None:
        """Forget everything — the engine's cache-recovery path (a reset
        pool holds no valid K/V, so no prefix may be matched)."""
        self._map.clear()
        self._rev.clear()
        self._tails.clear()
        self._rev_tails.clear()


# ---- the host paging tier (ISSUE 13) -------------------------------------
#
# Residency per chip is capped by HBM: the device page pool bounds
# concurrent users and aggregate context length, and the dtype ladder
# already took in-HBM bytes/token as low as it goes.  The tier below
# extends the SOSP '23 paged design one level down the memory hierarchy:
# cold pages spill into page-shaped pinned-host buffers
# (native/hostpool.py — the reference's L2 host_allocator lineage) and
# prefetch back ahead of the decode sweep, so the device pool holds only
# the pages the next sweeps touch while the host tier holds everything
# resident.  The engine drives WHEN (serve/engine.py: wave scheduling,
# prefetch one tick ahead, synchronous cold-hit fallback — and since
# ISSUE 19 the next wave's swap-in overlaps the RUNNING macro scan,
# issued after the dispatch and before its host sync, so the tier no
# longer clamps macro_steps to per-token dispatch); this module owns
# WHAT: the host store, the cross-tier refcount laws, and the residency
# policy.


class HostTierError(RuntimeError):
    """The host tier could not back an operation (buffer allocation
    failed, or capacity ran out) — the engine's spill path retries this
    through ``ft.retry`` and then DEGRADES to no-spill (device-only
    admission), so a host-tier outage shrinks capacity instead of
    corrupting state."""


class HostPageStore:
    """Page-granular host tier: ``n_pages`` page-record slots over bulk
    host buffers, with the :class:`PageAllocator` refcount laws.

    A page RECORD is one logical KV page's payload across every cache
    leaf and layer — for the fp32 rung ``k``/``v`` blocks of shape
    ``(n_layers, page_size, n_heads, d_head)``, plus the per-page scale
    rows ``(n_layers, n_heads)`` on the quantized rungs — packed
    contiguously so one spill moves one contiguous region.

    Backing is allocated LAZILY in spill-batch extents: the first write
    into k unbacked slots costs ONE ``HostPool.alloc_pages`` bulk
    buffer (not k allocations), regions are permanently bound to slots,
    and a freed slot keeps its region for reuse — so steady-state
    paging never re-allocates.  Without the native library the extents
    degrade to plain numpy (unpinned, same semantics).  ``alloc_hook``
    fires before every extent allocation — the ``serve/spill`` chaos
    injection point.

    Refcount laws (the allocator's, extended across tiers): ``put``
    grants refcount 1, ``share`` adds a holder to a live slot, ``free``
    drops one and reclaims at zero — so a spilled page shared k ways
    still counts one holder per sharer, and no holder's view can be
    reclaimed under it.

    EMPTY slots (``put_empty``) reserve capacity with no backing at
    all: a reserved-but-never-written budget-tail page has no payload
    worth moving, so its "spill" is pure bookkeeping — zero bytes, no
    allocation, outage-immune."""

    def __init__(self, n_pages: int,
                 leaf_shapes: dict[str, tuple[tuple, object]],
                 pool=None,
                 alloc_hook: Optional[Callable[[int], None]] = None):
        if n_pages < 1:
            raise ValueError(f"n_pages must be >= 1, got {n_pages}")
        self.n_pages = n_pages
        self.pool = pool                      # native HostPool or None
        self.alloc_hook = alloc_hook
        self._leaves: dict[str, tuple[tuple, np.dtype, int]] = {}
        off = 0
        for name, (shape, dtype) in leaf_shapes.items():
            dt = np.dtype(dtype)
            self._leaves[name] = (tuple(shape), dt, off)
            off += int(np.prod(shape)) * dt.itemsize
        self.page_nbytes = off
        self._free_bare = list(range(n_pages - 1, -1, -1))
        self._free_backed: list[int] = []
        self._refs: dict[int, int] = {}
        self._region: dict[int, np.ndarray] = {}  # slot -> uint8 record
        self._empty: set[int] = set()             # live slots w/o payload
        self._extents: list = []                  # keep buffers alive
        self._spare_regions: list[np.ndarray] = []  # cut, not yet bound
        self._backed_bytes = 0
        self._backed_hw = 0
        self.spill_bytes = 0     # payload bytes written into the store
        self.prefetch_bytes = 0  # payload bytes read back out

    # ---- capacity & refcount laws (PageAllocator's, host-side) ---------

    @property
    def n_free(self) -> int:
        return len(self._free_bare) + len(self._free_backed)

    @property
    def n_live(self) -> int:
        return len(self._refs)

    def refcount(self, slot: int) -> int:
        return self._refs.get(slot, 0)

    def is_empty(self, slot: int) -> bool:
        """True for a live slot reserved with no payload."""
        return slot in self._empty

    def share(self, slots: Iterable[int]) -> None:
        slots = list(slots)
        for s in slots:
            if s not in self._refs:
                raise ValueError(
                    f"host page {s} is not live (cannot share a freed "
                    f"page; {len(self._refs)} live of {self.n_pages})"
                )
        for s in slots:
            self._refs[s] += 1

    def free(self, slots: Iterable[int]) -> list[int]:
        released = []
        for s in slots:
            if s not in self._refs:
                raise ValueError(
                    f"host page {s} is not live (double free or foreign "
                    f"id; {len(self._refs)} live of {self.n_pages})"
                )
            self._refs[s] -= 1
            if self._refs[s] == 0:
                del self._refs[s]
                self._empty.discard(s)
                if s in self._region:
                    self._free_backed.append(s)
                else:
                    self._free_bare.append(s)
                released.append(s)
        return released

    # ---- backing -------------------------------------------------------

    def _alloc_extent(self, n: int) -> None:
        """ONE bulk buffer for ``n`` fresh page regions (the spill-batch
        shape).  Failures surface as :class:`HostTierError`."""
        nbytes = n * self.page_nbytes
        try:
            if self.alloc_hook is not None:
                self.alloc_hook(nbytes)
            if self.pool is not None:
                buf = self.pool.alloc_pages(n, self.page_nbytes)
                raw = buf.view(np.uint8)
            else:
                buf = None
                raw = np.empty(nbytes, np.uint8)
        except HostTierError:
            raise
        except Exception as exc:
            raise HostTierError(
                f"host tier extent of {nbytes} B failed: "
                f"{type(exc).__name__}: {exc}"
            ) from exc
        self._extents.append((buf, raw))
        self._backed_bytes += nbytes
        self._backed_hw = max(self._backed_hw, self._backed_bytes)
        for i in range(n - 1, -1, -1):
            # regions bind to slots at first use (in _take_backed)
            self._spare_regions.append(
                raw[i * self.page_nbytes:(i + 1) * self.page_nbytes]
            )

    def _take_backed(self, n: int) -> Optional[list[int]]:
        """``n`` live-able slots WITH regions bound, all-or-nothing:
        region-bearing free slots first (steady-state reuse — no
        allocation), then bare slots bound to fresh regions cut from
        ONE bulk extent.  None when fewer than ``n`` slots are free;
        the extent allocation happens BEFORE any slot leaves a free
        list, so a failed batch grants nothing."""
        if n > self.n_free:
            return None
        n_backed = min(n, len(self._free_backed))
        short = n - n_backed
        if short > len(self._spare_regions):
            self._alloc_extent(short - len(self._spare_regions))
        slots = [self._free_backed.pop() for _ in range(n_backed)]
        for _ in range(short):
            s = self._free_bare.pop()
            self._region[s] = self._spare_regions.pop()
            slots.append(s)
        return slots

    # ---- payload movement ----------------------------------------------

    def _views(self, slot: int) -> dict[str, np.ndarray]:
        region = self._region[slot]
        out = {}
        for name, (shape, dt, off) in self._leaves.items():
            n = int(np.prod(shape)) * dt.itemsize
            out[name] = np.frombuffer(
                region[off:off + n], dtype=dt
            ).reshape(shape)
        return out

    def put(self, payloads: dict[str, np.ndarray]) -> Optional[list[int]]:
        """Store a spill batch: every array carries the batch on axis 0
        (``(B, *per_page_shape)``).  Returns the granted slots at
        refcount 1, or None (granting nothing) when fewer than B slots
        are free; raises :class:`HostTierError` when slot capacity is
        there but backing cannot be allocated."""
        n = len(next(iter(payloads.values())))
        if n == 0:
            return []
        slots = self._take_backed(n)
        if slots is None:
            return None
        for i, s in enumerate(slots):
            views = self._views(s)
            for name, arr in payloads.items():
                views[name][...] = arr[i]
            self._refs[s] = 1
        moved = n * self.page_nbytes
        self.spill_bytes += moved
        if self.pool is not None:
            self.pool.note_spill(moved)
        return slots

    def put_empty(self, n: int) -> Optional[list[int]]:
        """Reserve ``n`` slots with NO payload (refcount 1) — the
        unwritten-page spill: capacity bookkeeping, zero bytes."""
        if n == 0:
            return []
        if n > self.n_free:
            return None
        slots = []
        for _ in range(n):
            s = (self._free_bare.pop() if self._free_bare
                 else self._free_backed.pop())
            self._refs[s] = 1
            self._empty.add(s)
            slots.append(s)
        return slots

    def read_batch(self, slots: Iterable[int]) -> dict[str, np.ndarray]:
        """Copy slot payloads back out, batch axis 0 — the prefetch
        read.  Empty slots are illegal here (nothing to read)."""
        slots = list(slots)
        for s in slots:
            if s not in self._refs:
                raise ValueError(f"host page {s} is not live")
            if s in self._empty:
                raise ValueError(f"host page {s} is empty (never written)")
        out = {
            name: np.stack([self._views(s)[name] for s in slots])
            for name in self._leaves
        }
        moved = len(slots) * self.page_nbytes
        self.prefetch_bytes += moved
        if self.pool is not None:
            self.pool.note_prefetch(moved)
        return out

    def stats(self) -> dict:
        """Footprint observable, not silent (the PR-11 metrics idiom)."""
        return {
            "n_pages": self.n_pages,
            "n_live": self.n_live,
            "n_free": self.n_free,
            "page_nbytes": self.page_nbytes,
            "backed_bytes": self._backed_bytes,
            "backed_bytes_hw": self._backed_hw,
            "spill_bytes": self.spill_bytes,
            "prefetch_bytes": self.prefetch_bytes,
        }

    def close(self) -> None:
        """Drop every region view, then return the bulk buffers to the
        host pool.  Only legal with no live slots: a closed store
        restarts cold — its freed slots lose their regions (back to the
        bare list), and the next spill batch cuts fresh extents."""
        if self._refs:
            raise ValueError(
                f"cannot close: {len(self._refs)} host page(s) still "
                f"live"
            )
        self._region.clear()
        self._spare_regions.clear()
        self._free_bare += self._free_backed
        self._free_backed.clear()
        extents, self._extents = self._extents, []
        import gc

        gc.collect()  # numpy views over ctypes blocks clear via cycles
        for buf, _raw in extents:
            if buf is not None:
                try:
                    buf.free()
                except ValueError:
                    pass  # a stray external view keeps it until GC
        self._backed_bytes = 0


def host_leaf_shapes(geom: CacheGeometry, dtype) -> dict:
    """Per-page host-record layout for one cache pool: what ONE logical
    page drags across the tiers — the K and V blocks of every layer
    plus, on the quantized rungs, their per-page per-head scale rows.
    The record byte count is exactly ``obs.ledger.kv_page_bytes`` of the
    pool (test-pinned), so static traffic accounting and actual store
    footprint can never drift apart."""
    dt = np.dtype(jnp.dtype(dtype))
    page = (geom.n_layers, geom.page_size, geom.n_heads, geom.d_head)
    out = {"k": (page, dt), "v": (page, dt)}
    if is_quantized_kv_dtype(dtype):
        srow = (geom.n_layers, geom.n_heads)
        out["k_scale"] = (srow, np.dtype(np.float32))
        out["v_scale"] = (srow, np.dtype(np.float32))
    return out


@dataclasses.dataclass(frozen=True)
class ResidencyPolicy:
    """WHICH pages stay device-resident: LRU by last-attended sweep,
    with a pinned hot window.

    - ``pin_tail``: the last N pages of every live sequence (its write
      frontier — touched by EVERY sweep it joins) are never chosen as
      spill victims, so steady decode cannot thrash its own hot window;
    - victims among the cold are ordered by ``(last_attended, page
      id)`` — least-recently-attended first, and among equals the
      OLDEST chunk of a context spills first (ids grow with position),
      which is exactly the long-context residency horizon: chunks past
      the horizon page out, the recent window stays hot."""

    pin_tail: int = 1

    def __post_init__(self):
        if self.pin_tail < 0:
            raise ValueError(f"pin_tail must be >= 0, got {self.pin_tail}")


class TieredPageAllocator:
    """Two-tier page allocator: LOGICAL pages whose backing moves
    between a device :class:`PageAllocator` and a :class:`HostPageStore`
    under a :class:`ResidencyPolicy` — the engine-facing currency
    (slot page lists, the prefix trie, copy-on-write) stays a logical
    id for the page's whole lifetime while its bytes migrate.

    The refcount laws are the :class:`PageAllocator`'s, extended across
    tiers: holders count on the LOGICAL page, so a spilled page shared
    k ways still counts one holder per sharer and neither tier can
    reclaim it; ``free`` drops one holder and the page's backing (in
    whichever tier) is reclaimed only at zero.

    Data movement is delegated: ``reader(device_ids) -> {leaf: (B,
    ...)}`` pulls page payloads off the device pool (the D2H spill leg)
    and ``writer(device_ids, payloads)`` lands them back (the H2D
    prefetch leg) — the engine binds these over its live cache pytree,
    so this class owns placement and laws, never jax buffers.

    A page is RESIDENT when device-backed; ``ensure_resident`` is the
    prefetch (and synchronous cold-hit) path, spilling LRU victims for
    room.  Reserved-but-unwritten pages (budget tails) spill and
    return as pure bookkeeping — no payload exists, so no bytes move
    and untiered garbage-page semantics are preserved exactly.

    PARKED pages extend the prefix trie's retention beyond page
    liveness: a freed trie-registered page can ``park`` (refcount 0,
    host-backed, evictable LRU cache) instead of dying, and a later
    trie hit ``restore_parked``s it into a fresh private logical page.

    ``degrade()`` is the host-tier outage contract: no further spills
    or parks, admission arithmetic collapses to device-only — the
    engine calls it after ``ft.retry`` exhausts on
    :class:`HostTierError`, making a total host outage behave exactly
    like an untiered engine."""

    def __init__(self, n_pages: int, store: Optional[HostPageStore],
                 reader: Callable, writer: Callable,
                 policy: Optional[ResidencyPolicy] = None,
                 on_parked_evict: Optional[Callable] = None):
        self._dev = PageAllocator(n_pages)
        self.n_pages = n_pages
        self.store = store
        self._reader, self._writer = reader, writer
        self.policy = policy or ResidencyPolicy()
        self._on_parked_evict = on_parked_evict
        self._next = 0
        self._loc: dict[int, tuple[str, int]] = {}  # lp -> (tier, id)
        self._refs: dict[int, int] = {}
        self._written: set[int] = set()
        self._last: dict[int, int] = {}             # lp -> sweep stamp
        self._pins: frozenset = frozenset()
        self._parked: dict[int, int] = {}           # lp -> park stamp
        self._clock = 0
        self.degraded = False
        self.spilled_pages = 0      # payload D2H copies
        self.prefetched_pages = 0   # payload H2D copies (incl. restores)
        self.spilled_empty = 0      # bookkeeping-only spills
        self.parked_hits = 0        # trie hits served from parked chains

    # ---- PageAllocator-compatible surface ------------------------------

    @property
    def n_free(self) -> int:
        """Unique reclaimable capacity ACROSS tiers (parked pages are
        reclaimable cache, so they count): after every holder frees and
        the parked pool drains, returns device + host capacity."""
        host = 0
        if self.store is not None and not self.degraded:
            host = self.store.n_free + len(self._parked)
        return self._dev.n_free + host

    @property
    def n_live(self) -> int:
        return len(self._refs)

    @property
    def n_parked(self) -> int:
        return len(self._parked)

    def refcount(self, lp: int) -> int:
        return self._refs.get(lp, 0)

    def is_resident(self, lp: int) -> bool:
        return self._loc[lp][0] == "dev"

    def is_parked(self, lp: int) -> bool:
        return lp in self._parked

    def device_page(self, lp: int) -> int:
        """The device id backing a RESIDENT logical page (table rows and
        copy-on-write read this after ``ensure_resident``)."""
        tier, i = self._loc[lp]
        if tier != "dev":
            raise ValueError(f"logical page {lp} is not device-resident")
        return i

    # ---- policy inputs (the engine narrates residency) -----------------

    def tick(self) -> None:
        """Advance the LRU clock (one engine tick)."""
        self._clock += 1

    def touch(self, lps: Iterable[int]) -> None:
        """Stamp pages as attended THIS sweep (the LRU recency input)."""
        for lp in lps:
            self._last[lp] = self._clock

    def mark_written(self, lps: Iterable[int]) -> None:
        """Pages now carry real K/V: their spills move payload (an
        unwritten page's spill is free, and its prefetch restores
        untiered garbage-page semantics — no copy either way)."""
        for lp in lps:
            self._written.add(lp)

    def set_pins(self, lps: Iterable[int]) -> None:
        """The pinned hot window (each live slot's tail pages) — never
        chosen as spill victims except as a correctness fallback when a
        sweep cannot otherwise seat its pages."""
        self._pins = frozenset(lps)

    # ---- allocation across tiers ---------------------------------------

    def _spill_candidates(self, keep: set, allow_pinned: bool) -> list[int]:
        """Victims in eviction order: resident LIVE pages outside
        ``keep``, LRU-by-last-attended (ties: lowest id = oldest chunk),
        pinned pages excluded unless ``allow_pinned``.  Empty under
        degrade: no host, nowhere to spill."""
        if self.store is None or self.degraded:
            return []
        cands = [
            lp for lp, (tier, _) in self._loc.items()
            if tier == "dev" and lp in self._refs and lp not in keep
            and (allow_pinned or lp not in self._pins)
        ]
        cands.sort(key=lambda lp: (self._last.get(lp, -1), lp))
        return cands

    def _host_room(self, n: int) -> bool:
        """Make ``n`` host slots available, evicting parked pages LRU
        (oldest park first) — parked chains are cache, reclaimable."""
        if self.store is None or self.degraded:
            return n == 0
        while self.store.n_free < n and self._parked:
            victim = min(self._parked, key=lambda lp: (self._parked[lp], lp))
            self._evict_parked(victim)
        return self.store.n_free >= n

    def _evict_parked(self, lp: int) -> None:
        del self._parked[lp]
        self.store.free([self._loc.pop(lp)[1]])
        self._written.discard(lp)
        self._last.pop(lp, None)
        if self._on_parked_evict is not None:
            self._on_parked_evict([lp])

    def _spill(self, victims: list[int]) -> None:
        """Move victims' backing device -> host as ONE batch: one bulk
        store write for the written ones (one extent allocation at
        most), pure bookkeeping for the unwritten ones, device ids
        freed.  All-or-nothing: a host-tier failure raises before any
        location changes."""
        if not victims:
            return
        if not self._host_room(len(victims)):
            raise HostTierError(
                f"host tier full: cannot spill {len(victims)} page(s) "
                f"({self.store.n_free if self.store else 0} free)"
            )
        written = [lp for lp in victims if lp in self._written]
        empty = [lp for lp in victims if lp not in self._written]
        slots_w: list[int] = []
        if written:
            payload = self._reader([self._loc[lp][1] for lp in written])
            got = self.store.put(payload)
            if got is None:
                raise HostTierError("host tier full mid-spill")
            slots_w = got
        slots_e = self.store.put_empty(len(empty)) if empty else []
        if slots_e is None:
            self.store.free(slots_w)
            raise HostTierError("host tier full mid-spill")
        for lp, s in zip(written, slots_w):
            self._dev.free([self._loc[lp][1]])
            self._loc[lp] = ("host", s)
        for lp, s in zip(empty, slots_e):
            self._dev.free([self._loc[lp][1]])
            self._loc[lp] = ("host", s)
        self.spilled_pages += len(written)
        self.spilled_empty += len(empty)

    def _make_room(self, n: int, keep: set, soft: bool = False) -> int:
        """Spill until ``n`` device pages are free (LRU victims outside
        ``keep``; pinned pages only as a last-resort correctness
        fallback).  Returns the free count achieved; raises
        :class:`HostTierError` when short unless ``soft``."""
        short = n - self._dev.n_free
        if short > 0:
            cands = self._spill_candidates(keep, allow_pinned=False)
            if len(cands) < short:
                cands = self._spill_candidates(keep, allow_pinned=True)
            take = cands[:short]
            if len(take) < short and not soft:
                raise HostTierError(
                    f"cannot make device room for {n} page(s): "
                    f"{self._dev.n_free} free, {len(cands)} spillable"
                )
            if soft and self.store is not None and not self.degraded:
                # best effort: spill what host capacity actually takes
                room = self.store.n_free + len(self._parked)
                take = take[:room]
            self._spill(take)
        return self._dev.n_free

    def _feasible(self, n: int, resident: int, keep: set) -> bool:
        """The alloc/watermark arithmetic, shared so the admission gate
        can never promise pages ``alloc`` then over-draws (the
        ``_share_plan`` discipline applied across tiers)."""
        if n <= 0:
            return True
        host_cap = 0
        if self.store is not None and not self.degraded:
            host_cap = self.store.n_free + len(self._parked)
        if self._dev.n_free + host_cap < n:
            return False
        dev_short = max(0, resident - self._dev.n_free)
        if dev_short > 0:
            cands = self._spill_candidates(keep, allow_pinned=True)
            if len(cands) < dev_short:
                return False
        # host slots: one per spilled victim + one per host-born page
        return dev_short + (n - resident) <= host_cap

    def _norm_resident(self, n: int, resident: Optional[int]) -> int:
        """Degrade (or a missing store) collapses to the untiered
        contract: everything allocates device-resident — host
        reservations need host capacity that no longer exists."""
        if self.store is None or self.degraded:
            return n
        return n if resident is None else min(resident, n)

    def can_alloc(self, n: int, resident: Optional[int] = None,
                  keep: Iterable[int] = ()) -> bool:
        """Pure twin of :meth:`alloc` — the engine's watermark gate."""
        return self._feasible(n, self._norm_resident(n, resident),
                              set(keep))

    def alloc(self, n: int = 1, resident: Optional[int] = None,
              keep: Iterable[int] = ()) -> Optional[list[int]]:
        """Grant ``n`` logical pages at refcount 1, the first
        ``resident`` of them device-backed (spilling LRU victims for
        room) and the rest host-backed EMPTY reservations — or None,
        granting nothing, when the tiers cannot cover it.  ``resident``
        defaults to all (the write-now contract: prefill and
        copy-on-write targets must be on device); budget tails pass a
        smaller count and cost no device pages until their frontier
        arrives.  ``keep`` shields in-flight pages from the spill."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if n == 0:
            return []
        resident = self._norm_resident(n, resident)
        keep = set(keep)
        if not self._feasible(n, resident, keep):
            return None
        self._make_room(resident, keep)
        dids = self._dev.alloc(resident) if resident else []
        assert dids is not None
        host_n = n - resident
        slots: list[int] = []
        if host_n:
            if not self._host_room(host_n):
                self._dev.free(dids)
                raise HostTierError(
                    f"host tier full allocating {host_n} reserve page(s)"
                )
            got = self.store.put_empty(host_n)
            if got is None:
                self._dev.free(dids)
                raise HostTierError(
                    f"host tier full allocating {host_n} reserve page(s)"
                )
            slots = got
        lps = []
        for i in range(n):
            lp = self._next
            self._next += 1
            if i < resident:
                self._loc[lp] = ("dev", dids[i])
            else:
                self._loc[lp] = ("host", slots[i - resident])
            self._refs[lp] = 1
            self._last[lp] = self._clock
            lps.append(lp)
        return lps

    def share(self, lps: Iterable[int]) -> None:
        """Add one holder per LIVE logical page — tier-independent (the
        spilled-shared-page law: holders count on the logical page)."""
        lps = list(lps)
        for lp in lps:
            if lp not in self._refs:
                raise ValueError(
                    f"logical page {lp} is not live (cannot share a "
                    f"freed page; {len(self._refs)} live)"
                )
        for lp in lps:
            self._refs[lp] += 1

    def free(self, lps: Iterable[int],
             park: Iterable[int] = ()) -> list[int]:
        """Drop one holder per page; a page whose LAST holder left
        either PARKS (still trie-matchable from the host tier — pages
        named in ``park``, written, host tier healthy) or dies, and
        only the DEAD are returned (the engine drops exactly those from
        its prefix trie; parked entries stay matchable)."""
        park = set(park)
        dead = []
        for lp in lps:
            if lp not in self._refs:
                raise ValueError(
                    f"logical page {lp} is not live (double free or "
                    f"foreign id; {len(self._refs)} live)"
                )
            self._refs[lp] -= 1
            if self._refs[lp] > 0:
                continue
            del self._refs[lp]
            if (lp in park and lp in self._written
                    and self.store is not None and not self.degraded):
                try:
                    self._park(lp)
                    continue
                except HostTierError:
                    pass  # no host room: the chain dies like before
            self._release(lp)
            dead.append(lp)
        return dead

    def _release(self, lp: int) -> None:
        tier, i = self._loc.pop(lp)
        if tier == "dev":
            self._dev.free([i])
        else:
            self.store.free([i])
        self._written.discard(lp)
        self._last.pop(lp, None)

    # ---- parking (warm-prefix retention, PR-8 remainder) ---------------

    def _park(self, lp: int) -> None:
        """Refcount hit zero but the chain stays warm: host-resident,
        refcount 0, LRU-evictable.  Resident pages spill first (their
        payload is the thing being retained)."""
        if self._loc[lp][0] == "dev":
            if not self._host_room(1):
                raise HostTierError("host tier full: cannot park")
            payload = self._reader([self._loc[lp][1]])
            slots = self.store.put(payload)
            if slots is None:
                raise HostTierError("host tier full: cannot park")
            self._dev.free([self._loc[lp][1]])
            self._loc[lp] = ("host", slots[0])
            self.spilled_pages += 1
        self._parked[lp] = self._clock

    def restore_parked(self, lp: int,
                       keep: Iterable[int] = ()) -> Optional[int]:
        """A trie hit on a parked chain: copy the parked page's payload
        into a FRESH device-resident logical page (refcount 1, private
        to the requester — no copy-on-write ever needed on it) and
        return it; the parked original stays parked for later sharers
        (its LRU stamp refreshed).  None when no room."""
        if lp not in self._parked:
            raise ValueError(f"logical page {lp} is not parked")
        # read FIRST (read_batch stacks into an owned copy): the alloc
        # below may spill for room, and its parked-LRU eviction could
        # pick lp itself — the copy keeps the restore valid either way
        payload = self.store.read_batch([self._loc[lp][1]])

        def uncount_read():
            # un-count the speculative read: no page actually moved up,
            # and the three-way traffic agreement (engine counters x
            # page bytes == store bytes) must stay exact — including
            # when a transient extent fault makes ft.retry re-enter
            self.store.prefetch_bytes -= self.store.page_nbytes
            if self.store.pool is not None:
                self.store.pool.note_prefetch(-self.store.page_nbytes)

        try:
            fresh = self.alloc(1, resident=1, keep=keep)
        except Exception:
            uncount_read()
            raise
        if fresh is None:
            uncount_read()
            return None
        self._writer([self._loc[fresh[0]][1]], payload)
        self._written.add(fresh[0])
        if lp in self._parked:  # survived the alloc: refresh its LRU
            self._parked[lp] = self._clock
        self.prefetched_pages += 1
        self.parked_hits += 1
        return fresh[0]

    def drop_parked(self) -> list[int]:
        """Evict every parked page (the cache-recovery path: a rebuilt
        pool holds no valid K/V anywhere)."""
        lps = sorted(self._parked)
        for lp in lps:
            del self._parked[lp]
            self.store.free([self._loc.pop(lp)[1]])
            self._written.discard(lp)
            self._last.pop(lp, None)
        return lps

    # ---- residency (the spill/prefetch hot path) -----------------------

    def ensure_resident(self, lps: Iterable[int], keep: Iterable[int] = (),
                        best_effort: bool = False) -> int:
        """Prefetch every host-backed page in ``lps`` onto the device
        (ONE batched H2D write for the written ones; empty reservations
        just take a device id — garbage contents, exactly the untiered
        fresh-page semantics).  Returns how many pages actually moved
        payload — the synchronous caller's COLD-HIT count, zero when
        the prefetch-ahead already landed them.  ``best_effort`` (the
        prefetch-ahead leg) fetches what fits and leaves the rest cold
        instead of raising."""
        lps = list(lps)
        missing = [lp for lp in lps if self._loc[lp][0] == "host"
                   and lp not in self._parked]
        if not missing:
            return 0
        keep = set(keep) | set(lps)
        copied = 0
        # SWAP in rounds: a spill consumes a host slot that only frees
        # when a fetched page vacates its own — so when both tiers run
        # tight (aggregate residency near device + host), each round
        # spills at most the host headroom, fetches that many, and the
        # vacated slots fund the next round.  Each round still batches
        # (one store write, one scatter), so the bulk-extent contract
        # holds per round.
        while missing:
            take = min(len(missing), self._dev.n_free)
            if take == 0:
                headroom = 0
                if self.store is not None and not self.degraded:
                    headroom = self.store.n_free + len(self._parked)
                want = min(len(missing), max(1, headroom))
                try:
                    self._make_room(want, keep, soft=best_effort)
                except HostTierError:
                    if best_effort:
                        break
                    raise
                take = min(len(missing), self._dev.n_free)
                if take == 0:
                    if best_effort:
                        break
                    raise HostTierError(
                        f"no device room for {len(missing)} page(s)"
                    )
            batch, missing = missing[:take], missing[take:]
            dids = self._dev.alloc(take)
            assert dids is not None
            written = [(lp, d) for lp, d in zip(batch, dids)
                       if lp in self._written]
            if written:
                payload = self.store.read_batch(
                    [self._loc[lp][1] for lp, _ in written]
                )
                self._writer([d for _, d in written], payload)
            for lp, d in zip(batch, dids):
                self.store.free([self._loc[lp][1]])
                self._loc[lp] = ("dev", d)
            self.prefetched_pages += len(written)
            copied += len(written)
        return copied

    # ---- outage contract -----------------------------------------------

    def degrade(self) -> None:
        """Host-tier outage: stop spilling and parking; admission
        arithmetic collapses to the device pool (already host-backed
        LIVE pages stay prefetchable — reads need no allocation), so
        the engine behaves like an untiered one from here on."""
        self.degraded = True

    def stats(self) -> dict:
        out = {
            "device_free": self._dev.n_free,
            "n_live": self.n_live,
            "n_parked": self.n_parked,
            "spilled_pages": self.spilled_pages,
            "prefetched_pages": self.prefetched_pages,
            "spilled_empty": self.spilled_empty,
            "parked_hits": self.parked_hits,
            "degraded": self.degraded,
        }
        if self.store is not None:
            out["host"] = self.store.stats()
        return out
