"""A selective state-space (SSM) block — the second model family.

A minimal Mamba-shaped layer over the sequence-parallel recurrence
(parallel/ssm.py): input-dependent decay ``a_t = sigmoid(x_t W_a + c)``,
drive ``b_t = x_t W_b``, hidden scan ``h_t = a_t h_{t-1} + b_t`` carried
ACROSS sequence shards by ``ssm_scan``, and a readout with residual.
Where models.transformer composes ring attention + MoE over a (dp, sp)
mesh, this block is the recurrence-based long-context alternative: the
sequence axis shards the same way, but the cross-device traffic is O(n*D)
aggregates instead of rotating KV blocks.

Everything is plain lax, so jax.grad flows through the distributed scan
unmodified — the training-parity test checks the sharded gradient against
the single-device oracle.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from tpuscratch.parallel.ssm import local_scan, ssm_scan


@dataclasses.dataclass(frozen=True)
class SSMConfig:
    d_model: int = 16
    d_state: int = 32


def init_params(seed: int, cfg: SSMConfig) -> dict:
    k = jax.random.split(jax.random.PRNGKey(seed), 3)
    s_in = cfg.d_model ** -0.5
    return {
        "w_a": jax.random.normal(k[0], (cfg.d_model, cfg.d_state)) * s_in,
        # start decays near 1 (long memory): sigmoid(2) ~ 0.88
        "c_a": jnp.full((cfg.d_state,), 2.0),
        "w_b": jax.random.normal(k[1], (cfg.d_model, cfg.d_state)) * s_in,
        "w_out": jax.random.normal(k[2], (cfg.d_state, cfg.d_model))
        * cfg.d_state ** -0.5,
    }


def ssm_block(params: dict, x: jnp.ndarray, seq_axis: str | None) -> jnp.ndarray:
    """Apply the block to a (T_local, d_model) sequence shard.

    ``seq_axis`` names the mesh axis the sequence is sharded over; None
    runs the purely-local scan (the single-device oracle path).
    """
    a = jax.nn.sigmoid(x @ params["w_a"] + params["c_a"])
    b = x @ params["w_b"]
    if seq_axis is None:
        (_, cum_b), _ = local_scan(a, b)  # inclusive scan from h_{-1}=0
        h = cum_b
    else:
        h = ssm_scan(a, b, seq_axis)
    return x + h @ params["w_out"]
