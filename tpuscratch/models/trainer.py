"""Checkpointed training driver: the ML-side `checkpointed_stencil`.

Composes the framework's subsystems into one preemption-surviving
training loop: the composed transformer train step (models/transformer —
ring attention over sp, expert MoE over dp, grad + SGD in one compiled
program), atomic checkpointing (runtime/checkpoint), and rank-aware
logging. A run killed between chunks and re-invoked with the same
arguments resumes at ``latest_step`` and produces BIT-IDENTICAL params
to an uninterrupted run: deterministic data (seeded per step), identical
chunk boundaries, and an exact f32 round trip through the checkpoint
format — the same contract ``halo.driver.checkpointed_stencil`` proves
for the stencil side (tests/test_trainer.py kills a run to prove this
one).

Reference lineage: the reference trains nothing, but runs under
scheduler walltime kills with no way to continue (SURVEY.md §5,
"Checkpoint/resume: absent"); this driver is what that row owes at the
model layer.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tpuscratch.models.transformer import (
    TransformerConfig,
    init_adam_state,
    init_params,
    train_step,
    train_step_adam,
)
from tpuscratch.obs.metrics import CompileCounter, MetricsRegistry
from tpuscratch.obs.sink import NullSink
from tpuscratch.runtime import checkpoint


@functools.lru_cache(maxsize=8)
def _target_w(seed: int, d_model: int) -> np.ndarray:
    """The task's fixed linear map (seeded by the run, not the step, so
    the task is stationary); cached — it would otherwise be redrawn
    host-side every training step."""
    w = np.random.default_rng(seed).standard_normal((d_model, d_model))
    return (0.5 * w / np.sqrt(d_model)).astype(np.float32)


def synthetic_batch(seed: int, step: int, batch: int, seq: int, d_model: int):
    """Deterministic per-step batch: same (seed, step) -> same data, on
    any host — the property that makes resume bit-exact without a data
    loader state to checkpoint."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, step]))
    x = rng.standard_normal((batch, seq, d_model)).astype(np.float32)
    y = (x @ _target_w(seed, d_model)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def _cfg_fingerprint(cfg: TransformerConfig) -> str:
    """JSON-stable identity of the model config, stored in checkpoint
    metadata so a resume with a different architecture fails loudly
    instead of silently training a different model from restored
    weights."""
    fields = dataclasses.asdict(cfg)
    return ",".join(f"{k}={fields[k]}" for k in sorted(fields))


@dataclasses.dataclass(frozen=True)
class TrainReport:
    steps_run: int       # executed in THIS invocation (resume skips the rest)
    final_step: int
    losses: tuple[float, ...]  # loss at each save point, this invocation


def train(
    mesh: Mesh,
    cfg: TransformerConfig,
    steps: int,
    ckpt_dir: str,
    *,
    lr: float = 0.05,
    optimizer: str = "sgd",
    save_every: int = 10,
    batch: Optional[int] = None,
    seq: Optional[int] = None,
    seed: int = 0,
    keep: int = 3,
    log: Callable[[str], None] = lambda s: None,
    obs=None,
) -> tuple[dict, TrainReport]:
    """Run (or resume) ``steps`` training steps, checkpointing every
    ``save_every``. Returns (params, report). ``optimizer`` is 'sgd' or
    'adam'; Adam's moment state is checkpointed WITH the params (the
    full training state, sharded like the params), so resume is
    bit-identical for both.

    ``obs`` (an ``obs.sink.Sink``) turns on telemetry: one
    ``train/chunk`` event per save chunk — loss, grad-norm, tokens/s,
    step device time, compile count — plus a final ``train/run`` +
    metrics snapshot.  The grad-norm output is only compiled into the
    step when a sink is attached, so an uninstrumented run's program is
    unchanged; either way a ``CompileCounter`` hooks the step body, so
    retrace-freedom across a run is observable (tests assert == 1)."""
    if save_every < 1:
        raise ValueError(f"save_every must be >= 1, got {save_every}")
    if optimizer not in ("sgd", "adam"):
        raise ValueError(f"optimizer must be sgd|adam, got {optimizer!r}")
    dp_n = mesh.shape["dp"]
    sp_n = mesh.shape["sp"]
    batch = batch if batch is not None else 2 * dp_n
    seq = seq if seq is not None else 8 * sp_n

    params = init_params(seed, cfg)
    opt = init_adam_state(params) if optimizer == "adam" else None
    start = 0
    if checkpoint.latest_step(ckpt_dir) is not None:
        # the bit-identical contract only holds if the resumed run replays
        # the same trajectory: fail loudly on a mismatched re-invocation —
        # batch/seq/cfg change the data stream and the compiled step just
        # as much as lr/seed do. Metadata is checked BEFORE any leaf load
        # so an architecture change surfaces as this error, not as a
        # leaf-count mismatch from restore.
        start, meta = checkpoint.peek_metadata(ckpt_dir)
        # pre-optimizer checkpoints hold bare params and trained with
        # SGD (the only format that existed): make that explicit so an
        # adam resume against one fails as a clear mismatch instead of
        # a leaf-count error from restore
        meta.setdefault("optimizer", "sgd")
        if start > steps:
            raise ValueError(
                f"checkpoint in {ckpt_dir} is at step {start}, beyond the "
                f"requested {steps} (use a fresh ckpt_dir)"
            )
        for key, val in (
            ("lr", lr), ("seed", seed), ("batch", batch), ("seq", seq),
            ("cfg", _cfg_fingerprint(cfg)), ("optimizer", optimizer),
        ):
            if key not in meta:
                # legacy checkpoint (pre-dates this key): resumable, but
                # the guard cannot vouch for this field — say so rather
                # than silently skipping the very check we promise
                import warnings

                warnings.warn(
                    f"resuming from a checkpoint without {key!r} in its "
                    f"metadata — cannot verify it matches this run's "
                    f"{key}={val}",
                    RuntimeWarning,
                    stacklevel=2,
                )
            elif meta[key] != val:
                raise ValueError(
                    f"resume mismatch: checkpoint has {key}={meta[key]}, "
                    f"this run asked for {val} (use a fresh ckpt_dir)"
                )
        state = {"params": params, "opt": opt} if opt is not None else params
        state, start, meta = checkpoint.restore(ckpt_dir, state, step=start)
        if opt is not None:
            params, opt = state["params"], state["opt"]
        else:
            params = state
        log(f"resumed at step {start} (meta {meta})")

    sink = obs if obs is not None else NullSink()
    want_gnorm = sink.enabled
    metrics = MetricsRegistry()
    counter = CompileCounter()
    sink.emit(
        "train/config",
        steps=steps, lr=lr, optimizer=optimizer, batch=batch, seq=seq,
        seed=seed, resumed_at=start, cfg=_cfg_fingerprint(cfg),
    )
    if optimizer == "adam":
        adam_fn = train_step_adam(mesh, cfg, lr=lr, counter=counter,
                                  with_grad_norm=want_gnorm)
    else:
        sgd_fn = train_step(mesh, cfg, lr=lr, counter=counter,
                            with_grad_norm=want_gnorm)
    losses = []
    ran = 0
    run_t0 = time.perf_counter()
    while start < steps:
        chunk = min(save_every, steps - start)
        loss = gnorm = None
        t0 = time.perf_counter()
        for i in range(chunk):
            x, y = synthetic_batch(seed, start + i, batch, seq, cfg.d_model)
            if optimizer == "adam":
                params, opt, loss, *rest = adam_fn(params, opt, x, y)
            else:
                params, loss, *rest = sgd_fn(params, x, y)
            gnorm = rest[0] if rest else None
        start += chunk
        ran += chunk
        loss_f = float(jax.block_until_ready(loss))
        chunk_s = time.perf_counter() - t0  # fenced by the loss readback
        losses.append(loss_f)
        metrics.counter("train/steps").inc(chunk)
        metrics.gauge("train/loss").set(loss_f)
        metrics.histogram("train/step_s").observe(chunk_s / chunk)
        metrics.gauge("train/compiles").set(counter.count)
        chunk_ev = {
            "step": start, "loss": loss_f,
            "step_s": round(chunk_s / chunk, 6),
            "steps_per_s": round(chunk / chunk_s, 3),
            "tokens_per_s": round(chunk * batch * seq / chunk_s, 3),
            "compiles": counter.count,
        }
        if gnorm is not None:
            gnorm_f = float(gnorm)
            chunk_ev["grad_norm"] = gnorm_f
            metrics.gauge("train/grad_norm").set(gnorm_f)
        sink.emit("train/chunk", **chunk_ev)
        state = (
            {"params": params, "opt": opt} if opt is not None else params
        )
        checkpoint.save(
            ckpt_dir, start, jax.tree.map(np.asarray, state),
            metadata={
                "steps_total": steps, "lr": lr, "seed": seed,
                "batch": batch, "seq": seq, "cfg": _cfg_fingerprint(cfg),
                "optimizer": optimizer,
            },
        )
        checkpoint.prune(ckpt_dir, keep)
        log(f"step {start}/{steps}: loss {loss_f:.5f}")
    sink.emit(
        "train/run",
        steps_run=ran, final_step=start,
        wall_s=round(time.perf_counter() - run_t0, 6),
        compiles=counter.count,
    )
    sink.emit_metrics(metrics.snapshot(), scope=metrics.id)
    sink.flush()
    return params, TrainReport(ran, start, tuple(losses))
