"""Checkpointed training driver: the ML-side `checkpointed_stencil`.

Composes the framework's subsystems into one preemption-surviving
training loop: the composed transformer train step (models/transformer —
ring attention over sp, expert MoE over dp, grad + SGD in one compiled
program), atomic checkpointing (runtime/checkpoint), and rank-aware
logging. A run killed between chunks and re-invoked with the same
arguments resumes at ``latest_step`` and produces BIT-IDENTICAL params
to an uninterrupted run: deterministic data (seeded per step), identical
chunk boundaries, and an exact f32 round trip through the checkpoint
format — the same contract ``halo.driver.checkpointed_stencil`` proves
for the stencil side (tests/test_trainer.py kills a run to prove this
one).

Reference lineage: the reference trains nothing, but runs under
scheduler walltime kills with no way to continue (SURVEY.md §5,
"Checkpoint/resume: absent"); this driver is what that row owes at the
model layer.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tpuscratch.models.transformer import (
    TransformerConfig,
    init_params,
    train_step,
)
from tpuscratch.runtime import checkpoint


@functools.lru_cache(maxsize=8)
def _target_w(seed: int, d_model: int) -> np.ndarray:
    """The task's fixed linear map (seeded by the run, not the step, so
    the task is stationary); cached — it would otherwise be redrawn
    host-side every training step."""
    w = np.random.default_rng(seed).standard_normal((d_model, d_model))
    return (0.5 * w / np.sqrt(d_model)).astype(np.float32)


def synthetic_batch(seed: int, step: int, batch: int, seq: int, d_model: int):
    """Deterministic per-step batch: same (seed, step) -> same data, on
    any host — the property that makes resume bit-exact without a data
    loader state to checkpoint."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, step]))
    x = rng.standard_normal((batch, seq, d_model)).astype(np.float32)
    y = (x @ _target_w(seed, d_model)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


@dataclasses.dataclass(frozen=True)
class TrainReport:
    steps_run: int       # executed in THIS invocation (resume skips the rest)
    final_step: int
    losses: tuple[float, ...]  # loss at each save point, this invocation


def train(
    mesh: Mesh,
    cfg: TransformerConfig,
    steps: int,
    ckpt_dir: str,
    *,
    lr: float = 0.05,
    save_every: int = 10,
    batch: Optional[int] = None,
    seq: Optional[int] = None,
    seed: int = 0,
    keep: int = 3,
    log: Callable[[str], None] = lambda s: None,
) -> tuple[dict, TrainReport]:
    """Run (or resume) ``steps`` training steps, checkpointing every
    ``save_every``. Returns (params, report)."""
    if save_every < 1:
        raise ValueError(f"save_every must be >= 1, got {save_every}")
    dp_n = mesh.shape["dp"]
    sp_n = mesh.shape["sp"]
    batch = batch if batch is not None else 2 * dp_n
    seq = seq if seq is not None else 8 * sp_n

    params = init_params(seed, cfg)
    start = 0
    if checkpoint.latest_step(ckpt_dir) is not None:
        params, start, meta = checkpoint.restore(ckpt_dir, params)
        if start > steps:
            raise ValueError(
                f"checkpoint in {ckpt_dir} is at step {start}, beyond the "
                f"requested {steps} (use a fresh ckpt_dir)"
            )
        # the bit-identical contract only holds if the resumed run replays
        # the same trajectory: fail loudly on a mismatched re-invocation
        for key, val in (("lr", lr), ("seed", seed)):
            if key in meta and meta[key] != val:
                raise ValueError(
                    f"resume mismatch: checkpoint has {key}={meta[key]}, "
                    f"this run asked for {val} (use a fresh ckpt_dir)"
                )
        log(f"resumed at step {start} (meta {meta})")

    step_fn = train_step(mesh, cfg, lr=lr)
    losses = []
    ran = 0
    while start < steps:
        chunk = min(save_every, steps - start)
        loss = None
        for i in range(chunk):
            x, y = synthetic_batch(seed, start + i, batch, seq, cfg.d_model)
            params, loss = step_fn(params, x, y)
        start += chunk
        ran += chunk
        loss_f = float(jax.block_until_ready(loss))
        losses.append(loss_f)
        checkpoint.save(
            ckpt_dir, start, jax.tree.map(np.asarray, params),
            metadata={"steps_total": steps, "lr": lr, "seed": seed},
        )
        checkpoint.prune(ckpt_dir, keep)
        log(f"step {start}/{steps}: loss {loss_f:.5f}")
    return params, TrainReport(ran, start, tuple(losses))
