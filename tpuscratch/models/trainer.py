"""Checkpointed training driver: the ML-side `checkpointed_stencil`.

Composes the framework's subsystems into one preemption-surviving
training loop: the composed transformer train step (models/transformer —
ring attention over sp, expert MoE over dp, grad + SGD in one compiled
program), atomic checkpointing (runtime/checkpoint), and rank-aware
logging. A run killed between chunks and re-invoked with the same
arguments resumes at ``latest_step`` and produces BIT-IDENTICAL params
to an uninterrupted run: deterministic data (seeded per step), identical
chunk boundaries, and an exact f32 round trip through the checkpoint
format — the same contract ``halo.driver.checkpointed_stencil`` proves
for the stencil side (tests/test_trainer.py kills a run to prove this
one).

Reference lineage: the reference trains nothing, but runs under
scheduler walltime kills with no way to continue (SURVEY.md §5,
"Checkpoint/resume: absent"); this driver is what that row owes at the
model layer.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import math
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tpuscratch.ft.chaos import bind_sink
from tpuscratch.ft.guards import (
    STATUS_CLIPPED,
    STATUS_SKIPPED,
    GuardPolicy,
    GuardState,
)
from tpuscratch.ft.retry import DEFAULT_SAVE_RETRY, RetryPolicy
from tpuscratch.models.transformer import (
    TransformerConfig,
    init_adam_state,
    init_params,
    stack_layers,
    train_step,
    train_step_adam,
)
from tpuscratch.models.zero import (
    init_plan_zero_state,
    init_zero_adam_state,
    put_plan_state,
    put_zero_state,
    train_step_plan,
    train_step_zero,
)
from tpuscratch.parallel.plan import ShardingPlan
from tpuscratch.runtime.errors import CommError
from tpuscratch.obs.metrics import CompileCounter, MetricsRegistry
from tpuscratch.obs.sink import NullSink
from tpuscratch.obs.trace import FlightRecorder, emit_phase_totals
from tpuscratch.runtime import checkpoint
from tpuscratch.runtime.chunked import (
    ChunkedProgram,
    ChunkResult,
    WorkloadSink,
)


@functools.lru_cache(maxsize=8)
def _target_w(seed: int, d_model: int) -> np.ndarray:
    """The task's fixed linear map (seeded by the run, not the step, so
    the task is stationary); cached — it would otherwise be redrawn
    host-side every training step."""
    w = np.random.default_rng(seed).standard_normal((d_model, d_model))
    return (0.5 * w / np.sqrt(d_model)).astype(np.float32)


def synthetic_batch(seed: int, step: int, batch: int, seq: int, d_model: int):
    """Deterministic per-step batch: same (seed, step) -> same data, on
    any host — the property that makes resume bit-exact without a data
    loader state to checkpoint."""
    rng = np.random.default_rng(np.random.SeedSequence([seed, step]))
    x = rng.standard_normal((batch, seq, d_model)).astype(np.float32)
    y = (x @ _target_w(seed, d_model)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def _cfg_fingerprint(cfg: TransformerConfig) -> str:
    """JSON-stable identity of the model config, stored in checkpoint
    metadata so a resume with a different architecture fails loudly
    instead of silently training a different model from restored
    weights."""
    fields = dataclasses.asdict(cfg)
    return ",".join(f"{k}={fields[k]}" for k in sorted(fields))


def _saved_plan_identity(meta: dict) -> dict:
    """The normalized plan identity a checkpoint's metadata implies:
    the recorded ``plan`` when present (PR-7 checkpoints), else the
    ZeRO ``mesh_shape`` as a single-microbatch plan (PR-4 checkpoints
    predate plan metadata but record the sharded layout)."""
    plan = meta.get("plan")
    if plan is not None:
        return {"dp": int(plan.get("dp", 1)), "sp": int(plan.get("sp", 1)),
                "pp": int(plan.get("pp", 1)),
                "n_micro": int(plan.get("n_micro", 1))}
    ms = meta.get("mesh_shape") or {}
    return {"dp": int(ms.get("dp", 1)), "sp": int(ms.get("sp", 1)),
            "pp": int(ms.get("pp", 1)), "n_micro": 1}


def _restore_state(ckpt_dir: str, params, opt, step, mesh_shape=None,
                   reshard=False, live_plan=None):
    """Restore the full training state at ``step`` (params alone for
    SGD, params+moments for Adam/ZeRO) — the ONE restore/unpack sequence
    the entry resume and the guard rollback share.  ``mesh_shape`` (the
    ZeRO path) makes the checkpoint layer itself reject a checkpoint
    whose dp-sharded optimizer leaves were laid out for a different
    mesh — unless ``reshard`` is set, in which case the saved layout is
    loaded as-is and the ZeRO moment shards are REGROUPED onto
    ``live_plan`` via ``models.zero.reshard_state`` (the elastic resume
    path).  Returns (params, opt, step, metadata)."""
    state = {"params": params, "opt": opt} if opt is not None else params
    state, step, meta = checkpoint.restore(ckpt_dir, state, step=step,
                                           mesh_shape=mesh_shape,
                                           reshard=reshard)
    if opt is None:
        return state, opt, step, meta
    params_r, opt_r = state["params"], state["opt"]
    if reshard and live_plan is not None and isinstance(opt_r, dict) \
            and "mu_flat" in opt_r:
        from tpuscratch.models.zero import reshard_state

        saved_plan = _saved_plan_identity(meta)
        if saved_plan != live_plan:
            opt_r = reshard_state(opt_r, params_r, saved_plan, live_plan)
    return params_r, opt_r, step, meta


@dataclasses.dataclass(frozen=True)
class TrainReport:
    steps_run: int       # committed in THIS invocation (resume skips the
    #                      rest; rolled-back chunks don't count)
    final_step: int
    losses: tuple[float, ...]  # loss at each save point, this invocation
    # guard ladder counts (zero when no guard was attached)
    skipped: int = 0
    clipped: int = 0
    rollbacks: int = 0


def train(
    mesh: Mesh,
    cfg: TransformerConfig,
    steps: int,
    ckpt_dir: str,
    *,
    lr: float = 0.05,
    optimizer: str = "sgd",
    save_every: int = 10,
    batch: Optional[int] = None,
    seq: Optional[int] = None,
    seed: int = 0,
    keep: int = 3,
    log: Callable[[str], None] = lambda s: None,
    obs=None,
    recorder: Optional[FlightRecorder] = None,
    chaos=None,
    guard: Optional[GuardPolicy | GuardState] = None,
    save_retry: Optional[RetryPolicy] = None,
    zero: bool = False,
    accum_steps: int = 1,
    plan: Optional[ShardingPlan] = None,
    reshard: bool = False,
    async_ckpt: bool = False,
) -> tuple[dict, TrainReport]:
    """Run (or resume) ``steps`` training steps, checkpointing every
    ``save_every``. Returns (params, report). ``optimizer`` is 'sgd' or
    'adam'; Adam's moment state is checkpointed WITH the params (the
    full training state, sharded like the params), so resume is
    bit-identical for both.

    ``obs`` (an ``obs.sink.Sink``) turns on telemetry: one
    ``train/chunk`` event per save chunk — loss, grad-norm, tokens/s,
    step device time, compile count — plus a final ``train/run`` +
    metrics snapshot.  The grad-norm output is only compiled into the
    step when a sink is attached, so an uninstrumented run's program is
    unchanged; either way a ``CompileCounter`` hooks the step body, so
    retrace-freedom across a run is observable (tests assert == 1).

    ``recorder`` (an ``obs.trace.FlightRecorder``; a fresh one is
    created when absent — the flight recorder is always-on and bounded)
    collects ``train/chunk`` / ``ckpt/save`` / ``train/rollback`` spans
    for Chrome-trace export; per-phase totals are emitted as cumulative
    ``trace/phase`` events through the sink at the end of the run (the
    straggler table's input).  The ``train/chunk`` event additionally
    carries ``steps``/``tokens``/``chunk_s``/``compile_s``, and every
    ``ckpt/save``/``ft/rollback`` event a duration, so ``obs.goodput``
    can partition the run's wall time from the artifact alone.

    Fault tolerance (all default-off; the uninstrumented program and
    loop are unchanged when absent):

    - ``chaos`` (an ``ft.ChaosPlan``) plugs the fault injector in:
      batch corruption per step (``train/grad``), transient CommErrors
      around the compiled step (``comm/train_step``), checkpoint-IO
      faults through ``save``'s stage hook (``ckpt/save``), and
      simulated preemption at chunk boundaries AFTER the save
      (``train/preempt`` — raises ``ft.Preempted`` for the supervisor).
    - ``guard`` (an ``ft.GuardPolicy``, or an ``ft.GuardState`` to keep
      one counter set across supervised restarts) compiles the
      device-side finiteness/spike/clip guard into the step and runs
      the host escalation ladder on the statuses read back each chunk:
      skipped steps apply nothing (in-program), over-norm steps apply
      clipped updates, and more than ``max_skips`` CONSECUTIVE skips
      roll the run back to the last checkpoint and replay the chunk
      (bounded by ``max_rollbacks``, then ``ft.GuardFailure``).
    - ``save_retry`` (an ``ft.RetryPolicy``) wraps every checkpoint
      save; defaults on when ``chaos`` is attached so injected IO
      faults are absorbed rather than fatal.

    ``zero=True`` (requires ``optimizer='adam'``) selects the
    ZeRO-sharded path (``models.zero``): gradients reduce-scatter over
    "dp" instead of all-reducing, the Adam moments live as dp-sharded
    flat shards (optimizer HBM ÷ |dp|, updated in place via buffer
    donation), and updated params are all-gathered for the next
    forward.  The checkpoint then holds the SHARDED optimizer leaves
    and records the mesh shape — resuming on a mesh with a different
    |dp| raises a ``CommError`` instead of mis-loading.
    ``accum_steps=k`` (ZeRO only) folds k microbatches into each
    update with gradient accumulation, deferring the single
    reduce-scatter to the last microbatch; each step then consumes k
    consecutive entries of the deterministic batch stream, so
    ``accum_steps`` is part of the resume identity like ``batch``.

    ``plan`` (a ``parallel.ShardingPlan`` built over THIS mesh)
    replaces the hardcoded dp x sp assumption with the plan's axis
    mapping and schedule.  A dp x sp plan (no pp axis, or pp=1 with
    one microbatch) runs the EXACT legacy program — bit-identical —
    with the plan's overlap policy threaded into the ZeRO sync legs.
    A PIPELINED plan (``pp`` axis, ``n_micro`` microbatches) trains
    the stage-stacked model through the GPipe schedule composed with
    dp x sp (and, under ``zero=True``, with dp-sharded ZeRO moments and
    the bubble-filling decomposed grad sync) — one compiled step,
    ``optimizer='adam'`` required.  The checkpoint records the
    normalized plan identity; resuming under a mismatched plan raises
    a ``CommError``, the same contract as a mismatched-|dp| ZeRO
    restore.

    ``reshard=True`` is the elastic escape hatch for exactly those two
    ``CommError``s: a checkpoint whose ZeRO moments were laid out for a
    DIFFERENT plan/mesh (a preempted-and-shrunk slice) is loaded in its
    saved layout and regrouped onto this run's plan at restore time
    (``models.zero.reshard_state`` — gather-by-manifest, re-split by
    the live ``zero_state_spec``, recommitted to canonical shardings).
    The layout FAMILY must match (stage-stacked vs flat dp x sp), and
    ``batch``/``seq``/``seed``/... stay part of the resume identity —
    the regroup changes the layout of the state, never the trajectory.
    The resumed run is bit-identical to its own replay on the new plan.

    ``async_ckpt=True`` replaces the blocking checkpoint saves with the
    snapshot-then-publish path (``runtime.async_ckpt``): the step loop
    only pays the device→pinned-host copy (emitted as ``ckpt/snapshot``)
    while a background writer serializes and publishes through the same
    crash-consistent protocol (emitted as ``ckpt/write`` at its true
    end stamp) — published checkpoints are byte-identical to the
    blocking path's, at most one write is in flight, and the barrier is
    drained before each next snapshot, at preemption points, and at
    exit."""
    return train_program(
        mesh, cfg, steps, ckpt_dir, lr=lr, optimizer=optimizer,
        save_every=save_every, batch=batch, seq=seq, seed=seed, keep=keep,
        log=log, obs=obs, recorder=recorder, chaos=chaos, guard=guard,
        save_retry=save_retry, zero=zero, accum_steps=accum_steps,
        plan=plan, reshard=reshard, async_ckpt=async_ckpt,
    ).run()


def train_program(
    mesh: Mesh,
    cfg: TransformerConfig,
    steps: int,
    ckpt_dir: str,
    *,
    lr: float = 0.05,
    optimizer: str = "sgd",
    save_every: int = 10,
    batch: Optional[int] = None,
    seq: Optional[int] = None,
    seed: int = 0,
    keep: int = 3,
    log: Callable[[str], None] = lambda s: None,
    obs=None,
    recorder: Optional[FlightRecorder] = None,
    chaos=None,
    guard: Optional[GuardPolicy | GuardState] = None,
    save_retry: Optional[RetryPolicy] = None,
    zero: bool = False,
    accum_steps: int = 1,
    plan: Optional[ShardingPlan] = None,
    reshard: bool = False,
    async_ckpt: bool = False,
    workload: str = "train",
) -> ChunkedProgram:
    """:func:`train` as an UN-RUN ``runtime.chunked.ChunkedProgram`` —
    the steppable form a co-scheduler
    (``runtime.scheduler.MeshScheduler``) or
    ``ft.supervisor.supervise_program`` consumes.  All validation,
    checkpoint resume and step-function construction happens here,
    eagerly, so a mismatched resume fails at build time; each ``tick()``
    then runs one save chunk with the EXACT legacy event stream
    (``train/chunk``, the guard ladder's ``ft/*``,
    ``ckpt/save``/``ckpt/snapshot``) — every event additionally tagged
    ``workload=`` for per-job goodput accounting.  ``program.remake()``
    rebuilds it resumed from ``ckpt_dir`` — the restart factory the
    supervisor and the scheduler re-invoke after a preemption."""
    orig_guard = guard  # remake re-passes the caller's policy/state

    def remake():
        return train_program(
            mesh, cfg, steps, ckpt_dir, lr=lr, optimizer=optimizer,
            save_every=save_every, batch=batch, seq=seq, seed=seed,
            keep=keep, log=log, obs=obs, recorder=recorder, chaos=chaos,
            guard=orig_guard, save_retry=save_retry, zero=zero,
            accum_steps=accum_steps, plan=plan, reshard=reshard,
            async_ckpt=async_ckpt, workload=workload,
        )

    if save_every < 1:
        raise ValueError(f"save_every must be >= 1, got {save_every}")
    if optimizer not in ("sgd", "adam"):
        raise ValueError(f"optimizer must be sgd|adam, got {optimizer!r}")
    if zero and optimizer != "adam":
        raise ValueError("zero=True shards optimizer state: optimizer "
                         f"must be 'adam', got {optimizer!r}")
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if accum_steps > 1 and not zero:
        raise ValueError("accum_steps > 1 is the ZeRO path's "
                         "deferred-sync feature: pass zero=True")
    dp_ax, sp_ax = (plan.dp, plan.sp) if plan is not None else ("dp", "sp")
    if plan is not None and (
        tuple(plan.mesh.axis_names) != tuple(mesh.axis_names)
        or plan.mesh.devices.shape != mesh.devices.shape
    ):
        raise ValueError(
            f"plan was built for mesh {dict(plan.mesh.shape)}, train() "
            f"was handed mesh {dict(mesh.shape)} — build the plan over "
            f"the mesh you train on (its axes are validated there)"
        )
    pipelined = plan is not None and plan.pipelined
    if pipelined and optimizer != "adam":
        raise ValueError(
            "a pipelined plan trains with optimizer='adam' "
            f"(got {optimizer!r})"
        )
    if pipelined and accum_steps != 1:
        raise ValueError(
            "a pipelined plan already microbatches through n_micro; "
            "accum_steps must be 1"
        )
    dp_n = mesh.shape[dp_ax]
    sp_n = mesh.shape[sp_ax]
    pp_n = plan.pp_size if pipelined else 1
    batch = batch if batch is not None else 2 * dp_n
    seq = seq if seq is not None else 8 * sp_n
    if pipelined and (batch // dp_n) % plan.n_micro:
        raise ValueError(
            f"local batch {batch // dp_n} (batch {batch} / |dp| {dp_n}) "
            f"not divisible by the plan's n_micro {plan.n_micro}"
        )
    # normalized plan identity: a pp=1 single-microbatch plan IS the
    # legacy program, so the two resume interchangeably; anything
    # pipelined is its own state layout and data schedule
    plan_id = (plan.describe() if plan is not None else
               {"dp": int(dp_n), "sp": int(sp_n), "pp": 1, "n_micro": 1})
    if zero:
        mesh_shape = {"dp": int(dp_n), "sp": int(sp_n)}
        if pipelined:
            mesh_shape["pp"] = int(pp_n)
    else:
        mesh_shape = None

    def fresh_state():
        if pipelined:
            params = stack_layers(init_params(seed, cfg))
            opt = (put_plan_state(init_plan_zero_state(params, plan),
                                  plan, cfg)
                   if zero else init_adam_state(params))
            return params, opt
        params = init_params(seed, cfg)
        if zero:
            return params, put_zero_state(
                init_zero_adam_state(params, dp_n), mesh, cfg, dp=dp_ax
            )
        return params, (init_adam_state(params) if optimizer == "adam"
                        else None)

    def commit_opt(opt):
        """Re-commit restored optimizer state to its canonical device
        layout (donation aliasing needs committed shardings)."""
        if not zero:
            return opt
        if pipelined:
            return put_plan_state(opt, plan, cfg)
        return put_zero_state(opt, mesh, cfg, dp=dp_ax)

    params, opt = fresh_state()
    start = 0
    if checkpoint.latest_step(ckpt_dir) is not None:
        # the bit-identical contract only holds if the resumed run replays
        # the same trajectory: fail loudly on a mismatched re-invocation —
        # batch/seq/cfg change the data stream and the compiled step just
        # as much as lr/seed do. Metadata is checked BEFORE any leaf load
        # so an architecture change surfaces as this error, not as a
        # leaf-count mismatch from restore.
        start, meta = checkpoint.peek_metadata(ckpt_dir)
        # pre-optimizer checkpoints hold bare params and trained with
        # SGD (the only format that existed): make that explicit so an
        # adam resume against one fails as a clear mismatch instead of
        # a leaf-count error from restore
        meta.setdefault("optimizer", "sgd")
        # pre-ZeRO checkpoints are replicated single-microbatch runs
        meta.setdefault("zero", False)
        meta.setdefault("accum_steps", 1)
        if start > steps:
            raise ValueError(
                f"checkpoint in {ckpt_dir} is at step {start}, beyond the "
                f"requested {steps} (use a fresh ckpt_dir)"
            )
        mesh_mismatch = (zero and meta.get("mesh_shape") is not None
                         and meta["mesh_shape"] != mesh_shape)
        # the plan is part of the state's meaning: stage-stacked params,
        # (pp, dp)-sharded moments, and the microbatched data schedule
        # all depend on it — a mismatched plan fails with the same
        # CommError contract as a mismatched-|dp| ZeRO restore, unless
        # reshard=True regroups the state onto the live plan
        stored_plan = meta.get("plan")
        live_pipelined = plan_id["pp"] > 1 or plan_id["n_micro"] > 1
        stored_pipelined = stored_plan is not None and (
            stored_plan.get("pp", 1) > 1
            or stored_plan.get("n_micro", 1) > 1
        )
        if stored_plan is None and live_pipelined:
            raise CommError(
                "train/resume",
                f"checkpoint in {ckpt_dir} predates ShardingPlan "
                f"metadata (a legacy dp x sp run) — it cannot resume "
                f"under the pipelined plan {plan_id}, with or without "
                f"reshard (the stage-stacked params are a different "
                f"state structure, not a re-layout)",
            )
        plan_mismatch = stored_plan is not None and stored_plan != plan_id
        if (mesh_mismatch or plan_mismatch) \
                and stored_pipelined != live_pipelined:
            raise CommError(
                "train/resume",
                f"checkpoint in {ckpt_dir} was trained under plan "
                f"{stored_plan}, this run's plan is {plan_id} — the "
                f"stage-stacked and the flat dp x sp layouts are "
                f"different state STRUCTURES; reshard=True regroups "
                f"shards within a family, it cannot cross one",
            )
        if mesh_mismatch and not reshard:
            # the dp-sharded flat moments are laid out for ONE |dp|;
            # CommError (not ValueError) — this is a sharding-layout
            # failure, the class the comm/runtime layer owns
            raise CommError(
                "train/resume",
                f"checkpoint in {ckpt_dir} holds ZeRO optimizer state "
                f"sharded for mesh {meta['mesh_shape']}, this run's mesh "
                f"is {mesh_shape} — dp-sharded moments cannot be "
                f"re-laid-out implicitly; pass reshard=True to regroup "
                f"them onto this mesh at restore time (or resume on a "
                f"matching mesh)",
            )
        if plan_mismatch and not reshard:
            raise CommError(
                "train/resume",
                f"checkpoint in {ckpt_dir} was trained under plan "
                f"{stored_plan}, this run's plan is {plan_id} — the "
                f"stage/mesh layout of the state cannot be re-laid-out "
                f"implicitly; pass reshard=True to regroup it onto this "
                f"plan at restore time (or resume under a matching "
                f"plan)",
            )
        for key, val in (
            ("lr", lr), ("seed", seed), ("batch", batch), ("seq", seq),
            ("cfg", _cfg_fingerprint(cfg)), ("optimizer", optimizer),
            ("zero", zero), ("accum_steps", accum_steps),
        ):
            if key not in meta:
                # legacy checkpoint (pre-dates this key): resumable, but
                # the guard cannot vouch for this field — say so rather
                # than silently skipping the very check we promise
                import warnings

                warnings.warn(
                    f"resuming from a checkpoint without {key!r} in its "
                    f"metadata — cannot verify it matches this run's "
                    f"{key}={val}",
                    RuntimeWarning,
                    stacklevel=2,
                )
            elif meta[key] != val:
                raise ValueError(
                    f"resume mismatch: checkpoint has {key}={meta[key]}, "
                    f"this run asked for {val} (use a fresh ckpt_dir)"
                )
        params, opt, start, meta = _restore_state(
            ckpt_dir, params, opt, start, mesh_shape=mesh_shape,
            reshard=reshard, live_plan=plan_id,
        )
        opt = commit_opt(opt)
        log(f"resumed at step {start} (meta {meta})")

    sink = WorkloadSink(obs if obs is not None else NullSink(), workload)
    want_gnorm = sink.enabled
    metrics = MetricsRegistry()
    counter = CompileCounter()
    rec = recorder if recorder is not None else FlightRecorder()
    sink.emit(
        "train/config",
        steps=steps, lr=lr, optimizer=optimizer, batch=batch, seq=seq,
        seed=seed, resumed_at=start, cfg=_cfg_fingerprint(cfg),
    )
    # guard may be a policy (fresh counters) or a GuardState (shared
    # across supervised restarts, the ChaosPlan-persistence convention —
    # skip/clip/rollback counts then survive a preemption)
    if isinstance(guard, GuardState):
        guard_state, guard = guard, guard.policy
    else:
        guard_state = GuardState(guard) if guard is not None else None
    step_guard = guard.step_guard() if guard is not None else None
    if pipelined:
        step_fn = train_step_plan(plan, cfg, lr=lr, zero=zero,
                                  counter=counter,
                                  with_grad_norm=want_gnorm,
                                  guard=step_guard)
    elif zero:
        step_fn = train_step_zero(
            mesh, cfg, lr=lr, counter=counter, accum_steps=accum_steps,
            with_grad_norm=want_gnorm, guard=step_guard, dp=dp_ax,
            sp=sp_ax,
            overlap_blocks=plan.overlap_blocks if plan is not None else 0,
        )
    elif optimizer == "adam":
        step_fn = train_step_adam(mesh, cfg, lr=lr, counter=counter,
                                  with_grad_norm=want_gnorm,
                                  guard=step_guard, dp=dp_ax, sp=sp_ax)
    else:
        step_fn = train_step(mesh, cfg, lr=lr, counter=counter,
                             with_grad_norm=want_gnorm, guard=step_guard,
                             dp=dp_ax, sp=sp_ax)
    if chaos is not None:
        # injected faults land in the run's own event stream
        bind_sink(chaos, sink)
        # the collective wrapper: each step call may raise a transient
        # CommError — the supervisor's restartable class
        step_fn = chaos.wrap_collective(step_fn, "train_step")
    metadata = {
        "steps_total": steps, "lr": lr, "seed": seed,
        "batch": batch, "seq": seq, "cfg": _cfg_fingerprint(cfg),
        "optimizer": optimizer, "zero": zero, "accum_steps": accum_steps,
        "plan": plan_id,
    }
    if zero:
        metadata["mesh_shape"] = mesh_shape
    save_policy = save_retry if save_retry is not None else (
        DEFAULT_SAVE_RETRY if chaos is not None else None
    )
    losses: list[float] = []
    st = {"params": params, "opt": opt, "ran": 0,
          "ref_loss": float("nan")}  # spike baseline: previous chunk's loss
    run_t0 = time.perf_counter()

    def run_chunk(cp, pos):
        chunk = min(save_every, steps - pos)
        loss = gnorm = None
        statuses = []
        compile_s = 0.0
        params, opt = st["params"], st["opt"]
        for i in range(chunk):
            if accum_steps > 1:
                # each update consumes accum_steps consecutive entries
                # of the deterministic stream (at k=1 this is exactly
                # the legacy indexing, so trajectories line up)
                micro = [
                    synthetic_batch(seed, (pos + i) * accum_steps + j,
                                    batch, seq, cfg.d_model)
                    for j in range(accum_steps)
                ]
                x = jnp.stack([m[0] for m in micro])
                y = jnp.stack([m[1] for m in micro])
            else:
                x, y = synthetic_batch(seed, pos + i, batch, seq,
                                       cfg.d_model)
            if chaos is not None:
                x = chaos.corrupt_batch(x, pos + i)
            # compile detection: jit tracing + compilation run
            # synchronously inside the traced call, so the bracket around
            # a step whose CompileCounter ticked is compile-dominated
            # wall — the goodput report's "compile" badput bucket
            traced = counter.count
            step_t0 = time.perf_counter()
            if guard is not None:
                rl = jnp.asarray(st["ref_loss"], jnp.float32)
                if optimizer == "adam":
                    params, opt, loss, gnorm, gst = step_fn(params, opt, x,
                                                            y, rl)
                else:
                    params, loss, gnorm, gst = step_fn(params, x, y, rl)
                statuses.append(gst)
            elif optimizer == "adam":
                params, opt, loss, *rest = step_fn(params, opt, x, y)
                gnorm = rest[0] if rest else None
            else:
                params, loss, *rest = step_fn(params, x, y)
                gnorm = rest[0] if rest else None
            if counter.count > traced:
                compile_s += time.perf_counter() - step_t0
        loss_f = float(jax.block_until_ready(loss))  # fences the span
        st["params"], st["opt"] = params, opt
        return chunk, loss_f, gnorm, statuses, compile_s

    def make_event(cp, pos, payload, chunk_sp):
        chunk, loss_f, gnorm, statuses, compile_s = payload
        chunk_sp.args["compile_s"] = round(compile_s, 6)
        chunk_s = chunk_sp.seconds
        if guard is not None:
            st_host = [int(s) for s in statuses]
            skips = st_host.count(STATUS_SKIPPED)
            clips = st_host.count(STATUS_CLIPPED)
            if skips or clips:
                metrics.counter("ft/skipped_steps").inc(skips)
                metrics.counter("ft/clipped_steps").inc(clips)
                cp.sink.emit("ft/guard", step=pos + chunk, skipped=skips,
                             clipped=clips)
            if guard_state.observe(st_host):
                # the stream is poisoned, not glitched: discard this
                # chunk, restore the last committed state, replay
                guard_state.rolled_back()  # GuardFailure past the budget
                metrics.counter("ft/rollbacks").inc()
                rb_sp = cp.rec.open_span("train/rollback",
                                         from_step=pos + chunk)
                # the in-flight async write must publish before we ask
                # "what is the last committed step"
                cp.drain()
                rb_to = checkpoint.latest_step(ckpt_dir)
                if rb_to is None:
                    st["params"], st["opt"] = fresh_state()
                    rb_to = 0
                else:
                    rb_p, rb_o, rb_to, _ = _restore_state(
                        ckpt_dir, st["params"], st["opt"], rb_to,
                        mesh_shape=mesh_shape, reshard=reshard,
                        live_plan=plan_id,
                    )
                    st["params"], st["opt"] = rb_p, commit_opt(rb_o)
                cp.rec.close_span(rb_sp)
                # lost wall: the discarded chunk's compute + the restore
                # — the goodput "rollback" badput bucket
                cp.sink.emit("ft/rollback", from_step=pos + chunk,
                             to_step=rb_to,
                             lost_s=round(chunk_s + rb_sp.seconds, 6))
                log(f"guard rollback: step {pos + chunk} -> {rb_to}")
                st["ref_loss"] = float("nan")
                return ChunkResult(pos=rb_to, rollback=True)
        new = pos + chunk
        st["ran"] += chunk
        losses.append(loss_f)
        if math.isfinite(loss_f):
            st["ref_loss"] = loss_f
        metrics.counter("train/steps").inc(chunk)
        metrics.gauge("train/loss").set(loss_f)
        metrics.histogram("train/step_s").observe(chunk_s / chunk)
        metrics.gauge("train/compiles").set(counter.count)
        chunk_ev = {
            "step": new, "loss": loss_f,
            "steps": chunk,
            "tokens": chunk * accum_steps * batch * seq,
            "chunk_s": round(chunk_s, 6),
            "compile_s": round(compile_s, 6),
            "step_s": round(chunk_s / chunk, 6),
            "steps_per_s": round(chunk / chunk_s, 3),
            "tokens_per_s": round(
                chunk * accum_steps * batch * seq / chunk_s, 3
            ),
            "compiles": counter.count,
        }
        if gnorm is not None:
            gnorm_f = float(gnorm)
            chunk_ev["grad_norm"] = gnorm_f
            metrics.gauge("train/grad_norm").set(gnorm_f)
        return ChunkResult(pos=new, event=chunk_ev)

    def snapshot(cp, pos):
        state = ({"params": st["params"], "opt": st["opt"]}
                 if st["opt"] is not None else st["params"])
        return state, metadata

    def on_saved(cp, pos):
        log(f"step {pos}/{steps}: loss {losses[-1]:.5f}")

    def epilogue(cp):
        cp.sink.emit(
            "train/run",
            steps_run=st["ran"], final_step=cp.pos,
            wall_s=round(time.perf_counter() - run_t0, 6),
            compiles=counter.count,
        )
        emit_phase_totals(cp.sink, cp.rec)
        cp.sink.emit_metrics(metrics.snapshot(), scope=metrics.id)
        cp.sink.flush()
        gs = guard_state
        return st["params"], TrainReport(
            st["ran"], cp.pos, tuple(losses),
            skipped=gs.skips if gs else 0,
            clipped=gs.clips if gs else 0,
            rollbacks=gs.rollbacks if gs else 0,
        )

    return ChunkedProgram(
        workload=workload, prefix="train", total=steps, pos=start,
        run_chunk=run_chunk, make_event=make_event, snapshot=snapshot,
        epilogue=epilogue, on_saved=on_saved, preempt_site="train/preempt",
        ckpt_dir=ckpt_dir, keep=keep, save_retry=save_policy,
        write_retry=save_policy, async_ckpt=async_ckpt, sink=sink,
        recorder=rec, metrics=metrics, chaos=chaos, log=log, remake=remake,
    )
