"""ZeRO-sharded training step: reduce-scatter grad sync + dp-sharded
fused Adam + deferred-sync gradient accumulation.

The replicated path (``models.transformer.train_step_adam``) mirrors the
reference's distributed-reduction shape (mpicuda2-4: every rank reduces
to a full replicated result): gradients are all-reduced over
("dp", "sp") and every rank holds a complete copy of the params and both
Adam moments.  ZeRO (Rajbhandari et al., SC'20) is the TPU-native
evolution of that reduction, and this module implements its stage-1/2
form over the existing ``shard_map`` mesh:

- **reduce-scatter, not all-reduce**: the non-expert gradients are
  packed into ONE flat f32 vector (``transformer.pack_nonexpert``) and
  ``lax.psum_scatter``'d over "dp" — each rank receives only its
  ``1/|dp|`` shard, moving ``(n-1) * shard`` wire bytes where the
  all-reduce moved ``2(n-1)/n * full`` (half the gradient-leg traffic;
  ``obs.ledger.grad_sync_wire_bytes`` proves it statically);
- **dp-sharded optimizer state**: the Adam moments for the non-expert
  params live as flat per-rank shards (spec ``P(dp)``), so per-rank
  optimizer HBM divides by ``|dp|``; the update runs
  ``ops.adam.fused_adam_tree`` on the (w, g, m, v) shard quadruple.
  Expert leaves are ALREADY dp-sharded (different experts per rank) and
  keep their elementwise update and their ``psum`` over "sp" only;
- **trailing all-gather**: each rank updates only its param shard, then
  one tiled ``all_gather`` over "dp" rebuilds the replicated params the
  next forward needs;
- **deferred-sync accumulation** (``accum_steps=k``): the compiled step
  takes ``(k, B, S, d)`` microbatches, accumulates LOCAL gradient sums
  through a ``lax.scan`` with no gradient collectives inside the loop,
  and issues the single reduce-scatter (+ trailing all-gather) once —
  sync count per update stays 1 regardless of ``k``
  (tests assert the compiled program holds exactly one reduce-scatter).

Sharding note: the sp axis still holds COPIES of the non-expert
gradients, so the shard is ``psum``'d over "sp" after the scatter —
scatter-first ordering keeps that psum shard-sized, ``2(s-1)/s * N/d``
instead of ``2(s-1)/s * N``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.models.transformer import (
    EXPERT_LEAVES,
    LAYER_LEAVES,
    TransformerConfig,
    _adam_apply,
    _apply_guard,
    _is_expert_leaf,
    _loss,
    _validate_step_config,
    adam_alpha,
    expert_leaves,
    nonexpert_size,
    pack_nonexpert,
    param_spec,
    unpack_nonexpert,
)
from tpuscratch.ops.adam import fused_adam_tree

__all__ = [
    "init_zero_adam_state",
    "local_zero_state",
    "put_zero_state",
    "train_step_zero",
    "train_step_zero_fn",
    "zero_flat_size",
    "zero_state_bytes_per_rank",
    "zero_state_spec",
]

#: pad quantum per rank: shards stay multiples of 8 (f32 sublane), so
#: the fused kernel's band chooser never degenerates on awkward sizes
_SHARD_QUANTUM = 8


def zero_flat_size(n_elems: int, n_dp: int) -> int:
    """Padded length of the packed non-expert flat vector: the smallest
    multiple of ``n_dp * 8`` holding ``n_elems`` — every rank's shard is
    equal-sized and sublane-aligned."""
    q = n_dp * _SHARD_QUANTUM
    return -(-n_elems // q) * q


def init_zero_adam_state(params, n_dp: int) -> dict:
    """Fresh ZeRO Adam state for ``params`` on a ``|dp| = n_dp`` mesh:

    - ``mu_flat``/``nu_flat``: GLOBAL flat f32 moment vectors of
      :func:`zero_flat_size` elements, spec ``P(dp)`` — each rank
      stores only its shard (optimizer HBM ÷ ``|dp|``);
    - ``mu_exp``/``nu_exp``: per-expert-leaf moment lists, sharded over
      "dp" with their leaves exactly like :func:`init_adam_state` was;
    - ``t``: the replicated step count.
    """
    flat = zero_flat_size(nonexpert_size(params), n_dp)
    exp = expert_leaves(params)
    return {
        "mu_flat": jnp.zeros((flat,), jnp.float32),
        "nu_flat": jnp.zeros((flat,), jnp.float32),
        "mu_exp": [jnp.zeros_like(x) for x in exp],
        "nu_exp": [jnp.zeros_like(x) for x in exp],
        "t": jnp.zeros((), jnp.int32),
    }


def zero_state_spec(cfg: TransformerConfig, dp: str = "dp") -> dict:
    """PartitionSpec pytree for :func:`init_zero_adam_state`'s output."""
    n_exp = sum(1 for name in LAYER_LEAVES if name in EXPERT_LEAVES)
    exp = [P(dp)] * (n_exp * cfg.n_layers)
    return {
        "mu_flat": P(dp),
        "nu_flat": P(dp),
        "mu_exp": exp,
        "nu_exp": list(exp),
        "t": P(),
    }


def put_zero_state(state, mesh: Mesh, cfg: TransformerConfig,
                   dp: str = "dp"):
    """Commit a (host or restored) ZeRO state onto ``mesh`` with its
    canonical shardings — so the compiled step's donated optimizer
    buffers are actually reusable in place (an uncommitted host array
    cannot alias a dp-sharded output)."""
    spec = zero_state_spec(cfg, dp)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), spec,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(state, shardings)


def zero_state_bytes_per_rank(cfg: TransformerConfig, params,
                              n_dp: int) -> int:
    """Static per-rank optimizer-state footprint (bytes) of the ZeRO
    layout — both flat moment shards plus this rank's expert-leaf
    moments.  The accounting the memory-÷-|dp| acceptance test checks
    against live shard shapes."""
    shard = zero_flat_size(nonexpert_size(params), n_dp) // n_dp
    exp = sum(
        2 * x.size * jnp.dtype(x.dtype).itemsize // n_dp
        for x in expert_leaves(params)
    )
    return 2 * shard * 4 + exp


def local_zero_state(params_local, n_dp: int) -> dict:
    """Per-rank-shaped fresh ZeRO state for use INSIDE a shard_map body
    (throughput programs initialize their carry in-program): the flat
    moment leaves are one rank's shard, the expert leaves are the local
    expert slices ``params_local`` already holds."""
    flat = zero_flat_size(nonexpert_size(params_local), n_dp)
    exp = expert_leaves(params_local)
    return {
        "mu_flat": jnp.zeros((flat // n_dp,), jnp.float32),
        "nu_flat": jnp.zeros((flat // n_dp,), jnp.float32),
        "mu_exp": [jnp.zeros_like(x) for x in exp],
        "nu_exp": [jnp.zeros_like(x) for x in exp],
        "t": jnp.zeros((), jnp.int32),
    }


def _zero_grad_sync(grads, n: int, dp: str, sp: str, flat_size: int):
    """The ONE deferred gradient sync: pack the non-expert leaves flat,
    reduce-scatter over "dp" (each rank keeps its shard), psum the
    shard-sized result over the "sp" copy axis, and psum expert leaves
    over "sp" only (their dp copies are DIFFERENT experts) — everything
    divided by ``n`` exactly like ``_grad_reduce``.  Returns
    ``(g_shard, g_exp)``."""
    g_flat = pack_nonexpert(grads, flat_size)
    g_shard = lax.psum_scatter(g_flat, dp, scatter_dimension=0, tiled=True)
    g_shard = lax.psum(g_shard, sp) / n
    g_exp = [lax.psum(g, sp) / n for g in expert_leaves(grads)]
    return g_shard, g_exp


def _zero_grad_norm(g_shard, g_exp, dp: str):
    """Global L2 norm of the reduced (logical) gradient under the ZeRO
    layout: shard square-sums psum over "dp" (each rank holds 1/|dp| of
    the flat gradient; padding slots are zero), expert leaves psum over
    "dp" as in ``_grad_norm``.  Identical on every rank."""
    s = lax.psum(jnp.sum(jnp.square(g_shard)), dp)
    for g in g_exp:
        s = s + lax.psum(jnp.sum(jnp.square(g.astype(jnp.float32))), dp)
    return jnp.sqrt(s)


def train_step_zero_fn(cfg: TransformerConfig, lr: float = 1e-3,
                       b1: float = 0.9, b2: float = 0.999,
                       eps: float = 1e-8, sp: str = "sp", dp: str = "dp",
                       accum_steps: int = 1,
                       with_grad_norm: bool = False,
                       guard: tuple | None = None,
                       fused: bool = True):
    """The shard_map body: (params, opt, x, y) -> (params, opt, loss)
    (+ grad_norm when ``with_grad_norm``), with ``opt`` laid out by
    :func:`init_zero_adam_state`.

    ``accum_steps=k`` changes the data contract to ``x, y`` of shape
    ``(k, B, S, d)``: gradients accumulate locally through a scan and
    the single reduce-scatter (and trailing all-gather) runs once per
    update — sync count cut k-fold versus syncing every microbatch.

    ``guard=(clip_norm, spike_factor)``: same contract as
    ``train_step_adam_fn`` — (params, opt, x, y, ref_loss) ->
    (params, opt, loss, grad_norm, status); a skipped step freezes the
    flat moment shards, the expert moments, and the step count along
    with the params.

    ``fused=False`` swaps the flat-shard update from the pallas fused
    kernel to the same elementwise expression — the A/B the trajectory
    tests use to separate kernel drift from sharding drift."""
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def loss_and_grads(params, x, y):
        return jax.value_and_grad(_loss)(params, x, y, cfg, sp, dp)

    def core(params, opt, x, y):
        n_dp, n_sp = lax.axis_size(dp), lax.axis_size(sp)
        n = n_dp * n_sp
        if accum_steps == 1:
            loss, grads = loss_and_grads(params, x, y)
        else:
            def acc(carry, xy):
                loss_i, g_i = loss_and_grads(params, *xy)
                return (
                    carry[0] + loss_i,
                    jax.tree.map(jnp.add, carry[1], g_i),
                ), None

            zero_g = jax.tree.map(jnp.zeros_like, params)
            (loss_sum, g_sum), _ = lax.scan(
                acc, (jnp.float32(0.0), zero_g), (x, y)
            )
            loss = loss_sum / accum_steps
            grads = jax.tree.map(lambda g: g / accum_steps, g_sum)
        flat_size = zero_flat_size(nonexpert_size(params), n_dp)
        g_shard, g_exp = _zero_grad_sync(grads, n, dp, sp, flat_size)
        return loss, g_shard, g_exp, flat_size // n_dp

    def update(params, opt, g_shard, g_exp, shard_elems):
        n_dp = lax.axis_size(dp)
        t = opt["t"] + 1
        alpha = adam_alpha(t, lr, b1, b2)
        w_flat = pack_nonexpert(params, shard_elems * n_dp)
        w_shard = lax.dynamic_slice_in_dim(
            w_flat, lax.axis_index(dp) * shard_elems, shard_elems
        )
        if fused:
            nw, nmu, nnu = fused_adam_tree(
                [w_shard], [g_shard], [opt["mu_flat"]], [opt["nu_flat"]],
                alpha, b1, b2, eps,
            )
            w_shard, mu_flat, nu_flat = nw[0], nmu[0], nnu[0]
        else:
            w_shard, mu_flat, nu_flat = _adam_apply(
                w_shard, opt["mu_flat"], opt["nu_flat"], g_shard, alpha,
                b1, b2, eps,
            )
        exp_w, mu_exp, nu_exp = _adam_apply(
            expert_leaves(params), opt["mu_exp"], opt["nu_exp"], g_exp,
            alpha, b1, b2, eps,
        )
        # the trailing all-gather: replicated params for the next forward
        new_flat = lax.all_gather(w_shard, dp, tiled=True)
        new_params = unpack_nonexpert(new_flat, exp_w, params)
        new_opt = {
            "mu_flat": mu_flat, "nu_flat": nu_flat,
            "mu_exp": mu_exp, "nu_exp": nu_exp, "t": t,
        }
        return new_params, new_opt

    if guard is None:
        def step(params, opt, x, y):
            loss, g_shard, g_exp, shard_elems = core(params, opt, x, y)
            new_params, new_opt = update(params, opt, g_shard, g_exp,
                                         shard_elems)
            if with_grad_norm:
                return (new_params, new_opt, loss,
                        _zero_grad_norm(g_shard, g_exp, dp))
            return new_params, new_opt, loss

        return step

    clip_norm, spike_factor = guard

    def guarded_step(params, opt, x, y, ref_loss):
        loss, g_shard, g_exp, shard_elems = core(params, opt, x, y)
        gnorm = _zero_grad_norm(g_shard, g_exp, dp)
        ok, status, clipped = _apply_guard(
            loss, gnorm, {"flat": g_shard, "exp": g_exp}, ref_loss,
            clip_norm, spike_factor, dp, sp,
        )
        up_params, up_opt = update(params, opt, clipped["flat"],
                                   clipped["exp"], shard_elems)
        sel = lambda new, cur: jax.tree.map(  # noqa: E731
            lambda a, b: jnp.where(ok, a, b), new, cur
        )
        return sel(up_params, params), sel(up_opt, opt), loss, gnorm, status

    return guarded_step


def train_step_zero(
    mesh: Mesh,
    cfg: TransformerConfig,
    lr: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    dp: str = "dp",
    sp: str = "sp",
    accum_steps: int = 1,
    with_grad_norm: bool = False,
    counter=None,
    guard: tuple | None = None,
    fused: bool = True,
    donate: bool = True,
):
    """Compiled ZeRO training step over ``mesh``: jit'd
    fn(params, opt, x, y) -> (params, opt, loss) with ``opt`` from
    :func:`init_zero_adam_state` sharded by :func:`zero_state_spec`.
    Same optional surfaces as ``train_step_adam``: ``with_grad_norm``
    appends the replicated grad-norm scalar, ``counter`` hooks the body
    for the recompile detector, ``guard=(clip_norm, spike_factor)``
    builds the ft-guarded variant (params, opt, x, y, ref_loss) ->
    (params, opt, loss, grad_norm, status).

    ``accum_steps=k`` shapes x, y as ``(k, batch, seq, d)`` (microbatch
    axis unsharded) and defers the one gradient sync to the last
    microbatch.  ``donate=True`` (default) donates the optimizer-state
    argument, so the flat moment shards are updated IN PLACE — per-rank
    optimizer HBM stays at the ÷|dp| shard, never two copies; pass
    committed state (:func:`put_zero_state`) for the aliasing to land.
    """
    _validate_step_config(mesh, cfg, dp, sp)
    pspec = param_spec(cfg, dp)
    ospec = zero_state_spec(cfg, dp)
    dspec = P(dp, sp) if accum_steps == 1 else P(None, dp, sp)
    body = train_step_zero_fn(
        cfg, lr, b1, b2, eps, sp=sp, dp=dp, accum_steps=accum_steps,
        with_grad_norm=with_grad_norm, guard=guard, fused=fused,
    )
    if counter is not None:
        body = counter.wrap(body)
    if guard is not None:
        in_specs = (pspec, ospec, dspec, dspec, P())
        out = (pspec, ospec, P(), P(), P())
    else:
        in_specs = (pspec, ospec, dspec, dspec)
        out = (
            (pspec, ospec, P(), P()) if with_grad_norm
            else (pspec, ospec, P())
        )
    return run_spmd(
        mesh,
        body,
        in_specs,
        out,
        donate_argnums=(1,) if donate else (),
    )
