"""ZeRO-sharded training step: reduce-scatter grad sync + dp-sharded
fused Adam + deferred-sync gradient accumulation.

The replicated path (``models.transformer.train_step_adam``) mirrors the
reference's distributed-reduction shape (mpicuda2-4: every rank reduces
to a full replicated result): gradients are all-reduced over
("dp", "sp") and every rank holds a complete copy of the params and both
Adam moments.  ZeRO (Rajbhandari et al., SC'20) is the TPU-native
evolution of that reduction, and this module implements its stage-1/2
form over the existing ``shard_map`` mesh:

- **reduce-scatter, not all-reduce**: the non-expert gradients are
  packed into ONE flat f32 vector (``transformer.pack_nonexpert``) and
  ``lax.psum_scatter``'d over "dp" — each rank receives only its
  ``1/|dp|`` shard, moving ``(n-1) * shard`` wire bytes where the
  all-reduce moved ``2(n-1)/n * full`` (half the gradient-leg traffic;
  ``obs.ledger.grad_sync_wire_bytes`` proves it statically);
- **dp-sharded optimizer state**: the Adam moments for the non-expert
  params live as flat per-rank shards (spec ``P(dp)``), so per-rank
  optimizer HBM divides by ``|dp|``; the update runs
  ``ops.adam.fused_adam_tree`` on the (w, g, m, v) shard quadruple.
  Expert leaves are ALREADY dp-sharded (different experts per rank) and
  keep their elementwise update and their ``psum`` over "sp" only;
- **trailing all-gather**: each rank updates only its param shard, then
  one tiled ``all_gather`` over "dp" rebuilds the replicated params the
  next forward needs;
- **deferred-sync accumulation** (``accum_steps=k``): the compiled step
  takes ``(k, B, S, d)`` microbatches, accumulates LOCAL gradient sums
  through a ``lax.scan`` with no gradient collectives inside the loop,
  and issues the single reduce-scatter (+ trailing all-gather) once —
  sync count per update stays 1 regardless of ``k``
  (tests assert the compiled program holds exactly one reduce-scatter).

Sharding note: the sp axis still holds COPIES of the non-expert
gradients, so the shard is ``psum``'d over "sp" after the scatter —
scatter-first ordering keeps that psum shard-sized, ``2(s-1)/s * N/d``
instead of ``2(s-1)/s * N``.

Two extensions land on top (ISSUE 7, driven by ``parallel.plan``):

- **comm/compute overlap** (``overlap_blocks=k``): the one flat
  reduce-scatter and the one trailing all-gather decompose into ``k``
  independent per-block chains (RS_i -> fused update_i -> AG_i), so the
  scheduler can fly block i's gather while block i+1's update computes
  — the ``parallel.ring`` hop-overlap idiom applied to the sync legs
  (Wang et al., ASPLOS'23's decomposed-collective pattern).  The block
  layout is strided so each rank's shard stays CONTIGUOUS and
  element-identical to the serial schedule: params, moments, and
  checkpoints are bit-identical across overlap on/off, and total wire
  bytes are unchanged (k transfers of shard/k) — only the collective
  count/schedule moves, which ``obs.ledger`` asserts statically.
- **the pipelined plan step** (:func:`train_step_plan`): the GPipe
  microbatched loss (``transformer._pp_loss_fn`` over the plan's pp
  axis) composed with the SAME dp-sharded ZeRO machinery — each
  (stage, dp) rank packs ITS stage's non-expert gradients flat,
  reduce-scatters over dp, updates its 1/|dp| moment shard in place,
  and all-gathers within the stage.  Stages' sync chains are disjoint
  by construction, so under overlap the decomposed reduce-scatters
  drain into the schedule alongside other stages' chains instead of
  serializing after the pipeline flush (the bubble-filling grad sync).

The elastic third extension (ISSUE 11): :func:`reshard_state` is the
restore-time transform that regroups a checkpoint's flat dp-sharded
(or pp x dp stage-grouped) moment vectors onto a DIFFERENT live plan —
the piece that turns the mismatched-plan resume ``CommError`` into a
``reshard=True`` continuation for preempted-and-shrunk meshes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.models.transformer import (
    EXPERT_LEAVES,
    LAYER_LEAVES,
    TransformerConfig,
    _adam_apply,
    _adam_update,
    _apply_guard,
    _grad_reduce,
    _is_expert_leaf,
    _loss,
    _pp_loss_fn,
    _validate_pp,
    _validate_step_config,
    adam_alpha,
    adam_state_spec_pp,
    expert_leaves,
    nonexpert_size,
    pack_nonexpert,
    param_spec,
    param_spec_pp,
    unpack_nonexpert,
)
from tpuscratch.ops.adam import fused_adam_tree

__all__ = [
    "init_plan_zero_state",
    "init_zero_adam_state",
    "local_zero_state",
    "plan_zero_state_spec",
    "put_plan_state",
    "put_zero_state",
    "reshard_state",
    "train_step_plan",
    "train_step_plan_fn",
    "train_step_zero",
    "train_step_zero_fn",
    "zero_flat_size",
    "zero_state_bytes_per_rank",
    "zero_state_spec",
]

#: pad quantum per rank: shards stay multiples of 8 (f32 sublane), so
#: the fused kernel's band chooser never degenerates on awkward sizes
_SHARD_QUANTUM = 8


def zero_flat_size(n_elems: int, n_dp: int) -> int:
    """Padded length of the packed non-expert flat vector: the smallest
    multiple of ``n_dp * 8`` holding ``n_elems`` — every rank's shard is
    equal-sized and sublane-aligned."""
    q = n_dp * _SHARD_QUANTUM
    return -(-n_elems // q) * q


def init_zero_adam_state(params, n_dp: int) -> dict:
    """Fresh ZeRO Adam state for ``params`` on a ``|dp| = n_dp`` mesh:

    - ``mu_flat``/``nu_flat``: GLOBAL flat f32 moment vectors of
      :func:`zero_flat_size` elements, spec ``P(dp)`` — each rank
      stores only its shard (optimizer HBM ÷ ``|dp|``);
    - ``mu_exp``/``nu_exp``: per-expert-leaf moment lists, sharded over
      "dp" with their leaves exactly like :func:`init_adam_state` was;
    - ``t``: the replicated step count.
    """
    flat = zero_flat_size(nonexpert_size(params), n_dp)
    exp = expert_leaves(params)
    return {
        "mu_flat": jnp.zeros((flat,), jnp.float32),
        "nu_flat": jnp.zeros((flat,), jnp.float32),
        "mu_exp": [jnp.zeros_like(x) for x in exp],
        "nu_exp": [jnp.zeros_like(x) for x in exp],
        "t": jnp.zeros((), jnp.int32),
    }


def zero_state_spec(cfg: TransformerConfig, dp: str = "dp") -> dict:
    """PartitionSpec pytree for :func:`init_zero_adam_state`'s output."""
    n_exp = sum(1 for name in LAYER_LEAVES if name in EXPERT_LEAVES)
    exp = [P(dp)] * (n_exp * cfg.n_layers)
    return {
        "mu_flat": P(dp),
        "nu_flat": P(dp),
        "mu_exp": exp,
        "nu_exp": list(exp),
        "t": P(),
    }


def put_zero_state(state, mesh: Mesh, cfg: TransformerConfig,
                   dp: str = "dp"):
    """Commit a (host or restored) ZeRO state onto ``mesh`` with its
    canonical shardings — so the compiled step's donated optimizer
    buffers are actually reusable in place (an uncommitted host array
    cannot alias a dp-sharded output)."""
    spec = zero_state_spec(cfg, dp)
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), spec,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(state, shardings)


def zero_state_bytes_per_rank(cfg: TransformerConfig, params,
                              n_dp: int) -> int:
    """Static per-rank optimizer-state footprint (bytes) of the ZeRO
    layout — both flat moment shards plus this rank's expert-leaf
    moments.  The accounting the memory-÷-|dp| acceptance test checks
    against live shard shapes."""
    shard = zero_flat_size(nonexpert_size(params), n_dp) // n_dp
    exp = sum(
        2 * x.size * jnp.dtype(x.dtype).itemsize // n_dp
        for x in expert_leaves(params)
    )
    return 2 * shard * 4 + exp


def local_zero_state(params_local, n_dp: int) -> dict:
    """Per-rank-shaped fresh ZeRO state for use INSIDE a shard_map body
    (throughput programs initialize their carry in-program): the flat
    moment leaves are one rank's shard, the expert leaves are the local
    expert slices ``params_local`` already holds."""
    flat = zero_flat_size(nonexpert_size(params_local), n_dp)
    exp = expert_leaves(params_local)
    return {
        "mu_flat": jnp.zeros((flat // n_dp,), jnp.float32),
        "nu_flat": jnp.zeros((flat // n_dp,), jnp.float32),
        "mu_exp": [jnp.zeros_like(x) for x in exp],
        "nu_exp": [jnp.zeros_like(x) for x in exp],
        "t": jnp.zeros((), jnp.int32),
    }


def _plan_groups(plan: dict) -> tuple[int, int, bool]:
    """(pp, dp, pipelined-family?) of a normalized plan identity
    ``{dp, sp, pp, n_micro}`` — ``pp`` is the flat vector's STAGE-group
    count, the family flag whether the state rides the stage-stacked
    params layout (``ShardingPlan.pipelined``'s rule)."""
    pp = int(plan.get("pp", 1))
    n_micro = int(plan.get("n_micro", 1))
    return pp, int(plan["dp"]), (pp > 1 or n_micro > 1)


def reshard_state(opt, params, saved: dict, live: dict):
    """Regroup a restored ZeRO optimizer state from the checkpointed
    plan identity ``saved`` onto a DIFFERENT live plan ``live`` (both
    normalized ``{dp, sp, pp, n_micro}`` dicts, the shape
    ``ShardingPlan.describe`` / the trainer's checkpoint metadata
    record) — the elastic restore-time transform: a run preempted on
    plan A resumes on plan B with state element-identical to A's.

    The flat moment vectors are pure relayouts of the SAME elements:

    - gather-by-manifest: each of ``saved``'s pp stage groups is
      unpacked back into per-leaf moment arrays (the stage's packed
      non-expert leaves in tree order, padding dropped — padded slots
      carry zero moments forever, so truncation is exact);
    - re-split: the per-leaf moments are re-packed under ``live``'s
      stage grouping and re-padded to ``zero_flat_size`` of the live
      ``|dp|`` (every live rank's shard equal-sized and aligned again);
    - the expert moments and the step count are layout-invariant
      (saved global, mesh-sharded only at ``device_put`` time) and pass
      through untouched.

    Host-side and numpy-pure; the result is UNCOMMITTED — feed it to
    ``put_zero_state`` / ``put_plan_state`` to land the live
    ``NamedSharding``s (the donation-aliasing contract).  Only
    within-family regroups are possible: the pipelined (stage-stacked)
    and the flat dp x sp layouts store different PARAM structures, so a
    cross-family resume is a real format change and raises
    ``CommError``.
    """
    import numpy as np

    from tpuscratch.runtime.errors import CommError

    pp_a, dp_a, fam_a = _plan_groups(saved)
    pp_b, dp_b, fam_b = _plan_groups(live)
    if fam_a != fam_b:
        raise CommError(
            "ckpt/reshard",
            f"checkpointed plan {saved} and live plan {live} are "
            f"different state-layout families (stage-stacked vs flat "
            f"dp x sp) — reshard_state regroups shards, it cannot "
            f"migrate the params structure",
        )
    if pp_a == pp_b and dp_a == dp_b:
        return opt
    n = nonexpert_size(params)
    leaves = [
        leaf for path, leaf in jax.tree_util.tree_leaves_with_path(params)
        if not _is_expert_leaf(path)
    ]
    shapes = [tuple(np.shape(x)) for x in leaves]
    for pp in {pp_a, pp_b}:
        if pp > 1 and any(s[0] % pp for s in shapes):
            raise CommError(
                "ckpt/reshard",
                f"a stacked leaf's layer axis is not divisible by "
                f"pp={pp} (shapes {shapes})",
            )
    flat_a = zero_flat_size(n // pp_a, dp_a)

    def gather(vec):
        """saved-layout flat vector -> per-leaf moment arrays."""
        vec = np.asarray(vec, np.float32)
        if vec.shape != (pp_a * flat_a,):
            raise CommError(
                "ckpt/reshard",
                f"flat moment vector has {vec.shape[0]} elements, plan "
                f"{saved} implies {pp_a} stage(s) x {flat_a} — the "
                f"checkpoint does not match its recorded plan",
            )
        per = n // pp_a
        parts: list[list] = [[] for _ in leaves]
        for s in range(pp_a):
            seg = vec[s * flat_a: s * flat_a + per]
            off = 0
            for i, shape in enumerate(shapes):
                ln = int(np.prod(shape)) // pp_a
                sub = ((shape[0] // pp_a,) + shape[1:]) if pp_a > 1 \
                    else shape
                parts[i].append(seg[off:off + ln].reshape(sub))
                off += ln
        return [
            np.concatenate(p, axis=0) if pp_a > 1 else p[0] for p in parts
        ]

    def resplit(moments):
        """per-leaf moment arrays -> live-layout flat vector."""
        per = n // pp_b
        flat_b = zero_flat_size(per, dp_b)
        out = np.zeros((pp_b * flat_b,), np.float32)
        for s in range(pp_b):
            segs = []
            for m, shape in zip(moments, shapes):
                if pp_b > 1:
                    ls = shape[0] // pp_b
                    segs.append(np.ravel(m[s * ls:(s + 1) * ls]))
                else:
                    segs.append(np.ravel(m))
            out[s * flat_b: s * flat_b + per] = np.concatenate(segs)
        return out

    return {
        "mu_flat": resplit(gather(opt["mu_flat"])),
        "nu_flat": resplit(gather(opt["nu_flat"])),
        "mu_exp": [np.asarray(x) for x in opt["mu_exp"]],
        "nu_exp": [np.asarray(x) for x in opt["nu_exp"]],
        "t": np.asarray(opt["t"]),
    }


def _overlap_blocks(requested: int, shard_elems: int) -> int:
    """Effective block count for the decomposed sync legs: the largest
    ``k <= requested`` dividing the per-rank shard (shards are padded to
    multiples of 8, so 2/4/8 always divide).  ``requested <= 1`` keeps
    the serial (unchunked) schedule."""
    if requested <= 1 or shard_elems <= 1:
        return 1
    k = min(requested, shard_elems)
    while shard_elems % k:
        k -= 1
    return k


def _zero_grad_sync(grads, n: int, dp: str, sp: str, flat_size: int,
                    blocks: int = 1):
    """The ONE deferred gradient sync: pack the non-expert leaves flat,
    reduce-scatter over "dp" (each rank keeps its shard), psum the
    shard-sized result over the "sp" copy axis, and psum expert leaves
    over "sp" only (their dp copies are DIFFERENT experts) — everything
    divided by ``n`` exactly like ``_grad_reduce``.  Returns
    ``(g_shard, g_exp)``.

    ``blocks > 1`` is the overlap decomposition: ``blocks`` independent
    reduce-scatters of ``flat/blocks`` each, strided so block c of this
    rank's result covers flat positions ``[me*shard + c*cs, ...)`` —
    i.e. ``concat(blocks) == the serial shard``, element for element.
    Same total wire bytes, ``blocks``-way scheduling freedom;
    ``g_shard`` is then the list of block shards."""
    g_flat = pack_nonexpert(grads, flat_size)
    g_exp = [lax.psum(g, sp) / n for g in expert_leaves(grads)]
    if blocks > 1:
        n_dp = lax.axis_size(dp)
        cs = flat_size // n_dp // blocks
        g3 = g_flat.reshape(n_dp, blocks, cs)
        chunks = []
        for c in range(blocks):
            gc = g3[:, c, :].reshape(-1)
            s = lax.psum_scatter(gc, dp, scatter_dimension=0, tiled=True)
            chunks.append(lax.psum(s, sp) / n)
        return chunks, g_exp
    g_shard = lax.psum_scatter(g_flat, dp, scatter_dimension=0, tiled=True)
    g_shard = lax.psum(g_shard, sp) / n
    return g_shard, g_exp


def _zero_grad_norm(g_shard, g_exp, axes):
    """Global L2 norm of the reduced (logical) gradient under the ZeRO
    layout: shard square-sums psum over the sharding ``axes`` ("dp", or
    ("dp", stage) under a pipelined plan — each rank holds a disjoint
    slice of the flat gradient; padding slots are zero), expert leaves
    psum over the same axes as in ``_grad_norm``.  Identical on every
    rank.  ``g_shard`` may be the serial shard or the overlap block
    list (block square-sums total the shard's exactly)."""
    chunks = g_shard if isinstance(g_shard, (list, tuple)) else [g_shard]
    s = lax.psum(sum(jnp.sum(jnp.square(c)) for c in chunks), axes)
    for g in g_exp:
        s = s + lax.psum(jnp.sum(jnp.square(g.astype(jnp.float32))), axes)
    return jnp.sqrt(s)


def _zero_flat_update(w_flat, g_shard, mu, nu, alpha, b1, b2, eps,
                      dp: str, fused: bool):
    """Update this rank's shard of the packed flat vector and rebuild
    the full replicated vector via the trailing all-gather leg; returns
    ``(new_flat, new_mu, new_nu)``.

    ``g_shard`` an array: the serial schedule — one fused update on the
    whole shard, ONE tiled all-gather.  ``g_shard`` a block list: the
    overlap schedule — per-block update + per-block all-gather, blocks
    independent of each other, so block i's gather can fly while block
    i+1's update computes (and the decomposed reduce-scatters upstream
    likewise).  The strided block layout keeps each rank's elements
    identical to the serial schedule's, so params, moments, and
    checkpoints are bit-identical across overlap on/off; total gather
    wire bytes are unchanged (``k`` transfers of ``shard/k``)."""
    n_dp = lax.axis_size(dp)
    me = lax.axis_index(dp)
    shard = w_flat.size // n_dp

    def apply(wc, gc, mc, vc):
        if fused:
            nw, nm, nv = fused_adam_tree([wc], [gc], [mc], [vc],
                                         alpha, b1, b2, eps)
            return nw[0], nm[0], nv[0]
        return _adam_apply(wc, mc, vc, gc, alpha, b1, b2, eps)

    if not isinstance(g_shard, (list, tuple)):
        w_shard = lax.dynamic_slice_in_dim(w_flat, me * shard, shard)
        w_shard, mu, nu = apply(w_shard, g_shard, mu, nu)
        return lax.all_gather(w_shard, dp, tiled=True), mu, nu

    blocks = len(g_shard)
    cs = shard // blocks
    w_my = lax.dynamic_index_in_dim(
        w_flat.reshape(n_dp, blocks, cs), me, 0, keepdims=False
    )
    mu2, nu2 = mu.reshape(blocks, cs), nu.reshape(blocks, cs)
    gathered, new_mu, new_nu = [], [], []
    for c in range(blocks):
        wc, mc, vc = apply(w_my[c], g_shard[c], mu2[c], nu2[c])
        gathered.append(lax.all_gather(wc, dp, tiled=True).reshape(n_dp, cs))
        new_mu.append(mc)
        new_nu.append(vc)
    # (n_dp, blocks, cs) -> flat: position d*shard + c*cs + e — the
    # serial layout, rebuilt from the block gathers by pure relayout
    full = jnp.stack(gathered, axis=1).reshape(w_flat.size)
    return full, jnp.concatenate(new_mu), jnp.concatenate(new_nu)


def _zero_apply_update(params, opt, g_shard, g_exp, flat_size, lr, b1,
                       b2, eps, dp: str, fused: bool):
    """The full ZeRO parameter/optimizer update both step families
    share (dp x sp and the pipelined plan): flat-shard Adam + trailing
    all-gather through :func:`_zero_flat_update`, elementwise Adam on
    the local expert leaves, repacked into a params tree shaped like
    ``params``.  Returns ``(new_params, new_opt)``."""
    t = opt["t"] + 1
    alpha = adam_alpha(t, lr, b1, b2)
    w_flat = pack_nonexpert(params, flat_size)
    new_flat, mu_flat, nu_flat = _zero_flat_update(
        w_flat, g_shard, opt["mu_flat"], opt["nu_flat"], alpha, b1, b2,
        eps, dp, fused,
    )
    exp_w, mu_exp, nu_exp = _adam_apply(
        expert_leaves(params), opt["mu_exp"], opt["nu_exp"], g_exp,
        alpha, b1, b2, eps,
    )
    new_params = unpack_nonexpert(new_flat, exp_w, params)
    new_opt = {
        "mu_flat": mu_flat, "nu_flat": nu_flat,
        "mu_exp": mu_exp, "nu_exp": nu_exp, "t": t,
    }
    return new_params, new_opt


def train_step_zero_fn(cfg: TransformerConfig, lr: float = 1e-3,
                       b1: float = 0.9, b2: float = 0.999,
                       eps: float = 1e-8, sp: str = "sp", dp: str = "dp",
                       accum_steps: int = 1,
                       with_grad_norm: bool = False,
                       guard: tuple | None = None,
                       fused: bool = True,
                       overlap_blocks: int = 0):
    """The shard_map body: (params, opt, x, y) -> (params, opt, loss)
    (+ grad_norm when ``with_grad_norm``), with ``opt`` laid out by
    :func:`init_zero_adam_state`.

    ``accum_steps=k`` changes the data contract to ``x, y`` of shape
    ``(k, B, S, d)``: gradients accumulate locally through a scan and
    the single reduce-scatter (and trailing all-gather) runs once per
    update — sync count cut k-fold versus syncing every microbatch.

    ``guard=(clip_norm, spike_factor)``: same contract as
    ``train_step_adam_fn`` — (params, opt, x, y, ref_loss) ->
    (params, opt, loss, grad_norm, status); a skipped step freezes the
    flat moment shards, the expert moments, and the step count along
    with the params.

    ``fused=False`` swaps the flat-shard update from the pallas fused
    kernel to the same elementwise expression — the A/B the trajectory
    tests use to separate kernel drift from sharding drift.

    ``overlap_blocks=k`` (0/1 = off) decomposes the flat reduce-scatter
    and the trailing all-gather into ``k`` independent per-block
    RS -> update -> AG chains (see module docstring): same total wire
    bytes and BIT-identical results, ``k``-way scheduling freedom for
    comm/compute overlap."""
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def loss_and_grads(params, x, y):
        return jax.value_and_grad(_loss)(params, x, y, cfg, sp, dp)

    def core(params, opt, x, y):
        n_dp, n_sp = lax.axis_size(dp), lax.axis_size(sp)
        n = n_dp * n_sp
        if accum_steps == 1:
            loss, grads = loss_and_grads(params, x, y)
        else:
            def acc(carry, xy):
                loss_i, g_i = loss_and_grads(params, *xy)
                return (
                    carry[0] + loss_i,
                    jax.tree.map(jnp.add, carry[1], g_i),
                ), None

            zero_g = jax.tree.map(jnp.zeros_like, params)
            (loss_sum, g_sum), _ = lax.scan(
                acc, (jnp.float32(0.0), zero_g), (x, y)
            )
            loss = loss_sum / accum_steps
            grads = jax.tree.map(lambda g: g / accum_steps, g_sum)
        flat_size = zero_flat_size(nonexpert_size(params), n_dp)
        blocks = _overlap_blocks(overlap_blocks, flat_size // n_dp)
        g_shard, g_exp = _zero_grad_sync(grads, n, dp, sp, flat_size,
                                         blocks)
        return loss, g_shard, g_exp, flat_size

    def update(params, opt, g_shard, g_exp, flat_size):
        return _zero_apply_update(params, opt, g_shard, g_exp, flat_size,
                                  lr, b1, b2, eps, dp, fused)

    if guard is None:
        def step(params, opt, x, y):
            loss, g_shard, g_exp, flat_size = core(params, opt, x, y)
            new_params, new_opt = update(params, opt, g_shard, g_exp,
                                         flat_size)
            if with_grad_norm:
                return (new_params, new_opt, loss,
                        _zero_grad_norm(g_shard, g_exp, dp))
            return new_params, new_opt, loss

        return step

    clip_norm, spike_factor = guard

    def guarded_step(params, opt, x, y, ref_loss):
        loss, g_shard, g_exp, flat_size = core(params, opt, x, y)
        gnorm = _zero_grad_norm(g_shard, g_exp, dp)
        ok, status, clipped = _apply_guard(
            loss, gnorm, {"flat": g_shard, "exp": g_exp}, ref_loss,
            clip_norm, spike_factor, dp, sp,
        )
        up_params, up_opt = update(params, opt, clipped["flat"],
                                   clipped["exp"], flat_size)
        sel = lambda new, cur: jax.tree.map(  # noqa: E731
            lambda a, b: jnp.where(ok, a, b), new, cur
        )
        return sel(up_params, params), sel(up_opt, opt), loss, gnorm, status

    return guarded_step


def train_step_zero(
    mesh: Mesh,
    cfg: TransformerConfig,
    lr: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    dp: str = "dp",
    sp: str = "sp",
    accum_steps: int = 1,
    with_grad_norm: bool = False,
    counter=None,
    guard: tuple | None = None,
    fused: bool = True,
    donate: bool = True,
    overlap_blocks: int = 0,
):
    """Compiled ZeRO training step over ``mesh``: jit'd
    fn(params, opt, x, y) -> (params, opt, loss) with ``opt`` from
    :func:`init_zero_adam_state` sharded by :func:`zero_state_spec`.
    ``overlap_blocks=k`` selects the decomposed (comm/compute overlap)
    sync schedule — bit-identical results, same wire bytes, k-way
    scheduling freedom (see :func:`train_step_zero_fn`).
    Same optional surfaces as ``train_step_adam``: ``with_grad_norm``
    appends the replicated grad-norm scalar, ``counter`` hooks the body
    for the recompile detector, ``guard=(clip_norm, spike_factor)``
    builds the ft-guarded variant (params, opt, x, y, ref_loss) ->
    (params, opt, loss, grad_norm, status).

    ``accum_steps=k`` shapes x, y as ``(k, batch, seq, d)`` (microbatch
    axis unsharded) and defers the one gradient sync to the last
    microbatch.  ``donate=True`` (default) donates the optimizer-state
    argument, so the flat moment shards are updated IN PLACE — per-rank
    optimizer HBM stays at the ÷|dp| shard, never two copies; pass
    committed state (:func:`put_zero_state`) for the aliasing to land.
    """
    _validate_step_config(mesh, cfg, dp, sp)
    pspec = param_spec(cfg, dp)
    ospec = zero_state_spec(cfg, dp)
    dspec = P(dp, sp) if accum_steps == 1 else P(None, dp, sp)
    body = train_step_zero_fn(
        cfg, lr, b1, b2, eps, sp=sp, dp=dp, accum_steps=accum_steps,
        with_grad_norm=with_grad_norm, guard=guard, fused=fused,
        overlap_blocks=overlap_blocks,
    )
    if counter is not None:
        body = counter.wrap(body)
    if guard is not None:
        in_specs = (pspec, ospec, dspec, dspec, P())
        out = (pspec, ospec, P(), P(), P())
    else:
        in_specs = (pspec, ospec, dspec, dspec)
        out = (
            (pspec, ospec, P(), P()) if with_grad_norm
            else (pspec, ospec, P())
        )
    return run_spmd(
        mesh,
        body,
        in_specs,
        out,
        donate_argnums=(1,) if donate else (),
    )


# ---------------------------------------------------------------------------
# The pipelined plan step: dp x sp x pp GPipe loss + dp-sharded ZeRO moments
# ---------------------------------------------------------------------------


def init_plan_zero_state(stacked, plan) -> dict:
    """Fresh ZeRO Adam state for a PIPELINED plan's stacked params
    (``transformer.stack_layers`` layout):

    - ``mu_flat``/``nu_flat``: ``(|pp| * flat_stage,)`` f32 vectors,
      spec ``P((pp, dp))`` — each (stage, dp) rank stores only the
      1/|dp| shard of ITS stage's packed non-expert vector, so the
      non-expert optimizer HBM divides by ``|pp| * |dp|`` per rank;
    - ``mu_exp``/``nu_exp``: stacked expert-leaf moments, sharded
      ``P(pp, ep)`` with their leaves (layer axis over stages, expert
      axis over dp);
    - ``t``: the replicated step count.

    With ``|pp| = 1`` this is exactly :func:`init_zero_adam_state` on
    the stacked tree."""
    n_pp, n_dp = plan.pp_size, plan.dp_size
    per_stage = nonexpert_size(stacked) // n_pp
    flat = zero_flat_size(per_stage, n_dp)
    exp = expert_leaves(stacked)
    return {
        "mu_flat": jnp.zeros((n_pp * flat,), jnp.float32),
        "nu_flat": jnp.zeros((n_pp * flat,), jnp.float32),
        "mu_exp": [jnp.zeros_like(x) for x in exp],
        "nu_exp": [jnp.zeros_like(x) for x in exp],
        "t": jnp.zeros((), jnp.int32),
    }


def plan_zero_state_spec(cfg: TransformerConfig, plan) -> dict:
    """PartitionSpec pytree for :func:`init_plan_zero_state`'s output —
    built through the plan's logical-axis resolver (the pytree-path ->
    mesh-axes mapping), so the spec follows whatever axis names the
    plan mapped."""
    n_exp = sum(1 for name in LAYER_LEAVES if name in EXPERT_LEAVES)
    flat = plan.spec(("pp", "dp"))
    exp = [plan.spec("pp", "ep")] * n_exp
    return {
        "mu_flat": flat,
        "nu_flat": flat,
        "mu_exp": exp,
        "nu_exp": list(exp),
        "t": P(),
    }


def put_plan_state(state, plan, cfg: TransformerConfig):
    """Commit a (host or restored) plan-ZeRO state onto the plan's mesh
    with its canonical shardings — the :func:`put_zero_state` analogue
    for the pipelined layout (donated optimizer buffers must be
    committed to alias in place)."""
    spec = plan_zero_state_spec(cfg, plan)
    shardings = jax.tree.map(
        lambda s: NamedSharding(plan.mesh, s), spec,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(state, shardings)


def _pp_grad_norm(grads, dp: str, stage: str):
    """Global L2 norm of the reduced gradient under the STACKED
    (non-ZeRO) pp layout: every leaf is stage-sharded (different layers
    per stage), so local square sums psum over the stage axis; expert
    leaves additionally over dp (different experts per rank).
    Identical on every rank."""

    def leaf_sq(path, g):
        s = jnp.sum(jnp.square(g.astype(jnp.float32)))
        axes = (dp, stage) if _is_expert_leaf(path) else (stage,)
        return lax.psum(s, axes)

    sq = jax.tree_util.tree_map_with_path(leaf_sq, grads)
    return jnp.sqrt(sum(jax.tree_util.tree_leaves(sq)))


def train_step_plan_fn(cfg: TransformerConfig, n_micro: int = 2,
                       lr: float = 1e-3, b1: float = 0.9,
                       b2: float = 0.999, eps: float = 1e-8,
                       sp: str = "sp", dp: str = "dp", stage: str = "pp",
                       zero: bool = True, overlap_blocks: int = 0,
                       with_grad_norm: bool = False,
                       guard: tuple | None = None, fused: bool = True):
    """The 3-axis shard_map body the ShardingPlan selects:
    (stacked, opt, x, y) -> (stacked, opt, loss) (+ grad_norm / guard
    outputs), composing the GPipe microbatched loss
    (``transformer._pp_loss_fn`` — ring attention over sp, expert MoE
    over dp, ``n_micro`` microbatches streaming over the stage axis)
    with either

    - ``zero=True``: dp-sharded ZeRO moments — each (stage, dp) rank
      packs ITS stage's non-expert gradients flat, reduce-scatters over
      dp (psum over the sp copy axis, all divided by
      ``|dp|*|sp|*|pp|``), runs the fused Adam update on its shard, and
      all-gathers within the stage.  Per-stage sync chains are disjoint
      by construction; ``overlap_blocks=k`` further decomposes each
      into k independent RS -> update -> AG chains (the bubble-filling
      schedule: the flat sync drains alongside other stages' chains and
      the scheduler's remaining work instead of serializing after the
      pipeline flush) — same wire bytes, bit-identical results;
    - ``zero=False``: stacked replicated-per-stage Adam moments
      (``adam_state_spec_pp`` layout), classic ``_grad_reduce`` +
      ``/ |pp|`` reduction.

    ``guard=(clip_norm, spike_factor)``: the ft contract —
    (stacked, opt, x, y, ref_loss) -> (..., loss, grad_norm, status)
    with finiteness agreement extended over the stage axis, so a
    skip-select can never diverge stages."""
    loss_fn = _pp_loss_fn(cfg, n_micro, sp, dp, stage)

    def core(params, x, y):
        n_dp, n_pp = lax.axis_size(dp), lax.axis_size(stage)
        n = n_dp * lax.axis_size(sp) * n_pp
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        if zero:
            flat_size = zero_flat_size(nonexpert_size(params), n_dp)
            blocks = _overlap_blocks(overlap_blocks, flat_size // n_dp)
            g_shard, g_exp = _zero_grad_sync(grads, n, dp, sp, flat_size,
                                             blocks)
            return loss, (g_shard, g_exp, flat_size)
        grads = _grad_reduce(grads, dp, sp)
        if n_pp > 1:
            grads = jax.tree.map(lambda g: g / n_pp, grads)
        return loss, grads

    def update(params, opt, payload):
        if zero:
            g_shard, g_exp, flat_size = payload
            return _zero_apply_update(params, opt, g_shard, g_exp,
                                      flat_size, lr, b1, b2, eps, dp,
                                      fused)
        return _adam_update(params, opt, payload, lr, b1, b2, eps)

    def gnorm_of(payload):
        if zero:
            g_shard, g_exp, _ = payload
            return _zero_grad_norm(g_shard, g_exp, (dp, stage))
        return _pp_grad_norm(payload, dp, stage)

    if guard is None:
        def step(params, opt, x, y):
            loss, payload = core(params, x, y)
            new_params, new_opt = update(params, opt, payload)
            if with_grad_norm:
                return new_params, new_opt, loss, gnorm_of(payload)
            return new_params, new_opt, loss

        return step

    clip_norm, spike_factor = guard

    def guarded_step(params, opt, x, y, ref_loss):
        loss, payload = core(params, x, y)
        gnorm = gnorm_of(payload)
        if zero:
            g_shard, g_exp, flat_size = payload
            ok, status, clipped = _apply_guard(
                loss, gnorm, {"flat": g_shard, "exp": g_exp}, ref_loss,
                clip_norm, spike_factor, dp, sp, extra_axes=(stage,),
            )
            payload = (clipped["flat"], clipped["exp"], flat_size)
        else:
            ok, status, payload = _apply_guard(
                loss, gnorm, payload, ref_loss, clip_norm, spike_factor,
                dp, sp, extra_axes=(stage,),
            )
        up_params, up_opt = update(params, opt, payload)
        sel = lambda new, cur: jax.tree.map(  # noqa: E731
            lambda a, b: jnp.where(ok, a, b), new, cur
        )
        return sel(up_params, params), sel(up_opt, opt), loss, gnorm, status

    return guarded_step


def train_step_plan(
    plan,
    cfg: TransformerConfig,
    lr: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    zero: bool = True,
    with_grad_norm: bool = False,
    counter=None,
    guard: tuple | None = None,
    fused: bool = True,
    donate: bool = True,
):
    """Compiled plan-composed training step over ``plan.mesh``: jit'd
    fn(stacked, opt, x, y) -> (stacked, opt, loss) with the stacked
    layout from ``transformer.stack_layers`` sharded by
    ``param_spec_pp`` over the plan's pp/ep axes and ``opt`` from
    :func:`init_plan_zero_state` (``zero=True``; the optimizer arg is
    DONATED so the flat moment shards update in place — pass committed
    state, :func:`put_plan_state`) or ``init_adam_state`` on the
    stacked tree (``zero=False``).  Same optional surfaces as
    ``train_step_zero``: ``with_grad_norm``, ``counter``,
    ``guard=(clip_norm, spike_factor)``.

    The overlap schedule comes from the PLAN (``plan.overlap_blocks``):
    this is the one seam where the comm/compute-overlap policy and the
    axis mapping travel together into the compiled program."""
    mesh, dp, sp, stage = plan.mesh, plan.dp, plan.sp, plan.pp
    if stage is None:
        raise ValueError(
            "train_step_plan needs a pipelined plan (pp=<axis name>); "
            "a dp x sp plan trains through train_step_adam / "
            "train_step_zero"
        )
    if plan.ep_axis != plan.dp:
        raise NotImplementedError(
            "expert parallelism rides the dp axis (EP groups == DP "
            "groups); a distinct ep mesh axis is not supported yet"
        )
    _validate_pp(mesh, cfg, dp, sp, stage)
    pspec = param_spec_pp(cfg, stage, dp)
    ospec = (plan_zero_state_spec(cfg, plan) if zero
             else adam_state_spec_pp(cfg, stage, dp))
    dspec = plan.data_spec()
    body = train_step_plan_fn(
        cfg, plan.n_micro, lr, b1, b2, eps, sp=sp, dp=dp, stage=stage,
        zero=zero, overlap_blocks=plan.overlap_blocks,
        with_grad_norm=with_grad_norm, guard=guard, fused=fused,
    )
    if counter is not None:
        body = counter.wrap(body)
    if guard is not None:
        in_specs = (pspec, ospec, dspec, dspec, P())
        out = (pspec, ospec, P(), P(), P())
    else:
        in_specs = (pspec, ospec, dspec, dspec)
        out = (
            (pspec, ospec, P(), P()) if with_grad_norm
            else (pspec, ospec, P())
        )
    return run_spmd(
        mesh,
        body,
        in_specs,
        out,
        donate_argnums=(1,) if (donate and zero) else (),
    )
