"""Mini MoE transformer: one compiled training step composing dp+sp+ep.

The composed demonstration the parallel/* modules build toward — and the
thing the reference cannot express at all (it has no autodiff, no
optimizer, no attention; its closest structure is the exchange-compute
loop at /root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:92-95).
One ``shard_map``'d ``jax.grad`` step over a 2D mesh ("dp", "sp"):

- batch sharded over "dp", sequence over "sp";
- attention: ring attention over "sp" (parallel.ring_attention — KV
  blocks rotate by ppermute, optionally flash-kernel hops);
- MoE FFN: expert parallelism over the "dp" axis (the standard
  EP-groups==DP-groups layout; parallel.expert all_to_all
  dispatch/combine);
- loss: pmean over both axes; gradients: collective transposes route
  cross-rank cotangents (rotated KV, routed tokens) back to the owning
  rank, then an explicit per-leaf psum totals the copies — expert leaves
  over "sp" only (their copies live across "sp"; across "dp" they are
  DIFFERENT experts), replicated leaves over both axes;
- SGD update, all inside the same jit.

Everything is a pure function over an explicit parameter pytree — the
idiomatic JAX shape, not a port of any framework's Module system.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.parallel.expert import expert_parallel_ffn
from tpuscratch.parallel.pipeline import gpipe_scan
from tpuscratch.parallel.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    d_model: int = 32
    n_heads: int = 2
    n_experts: int = 4          # total; must divide by the dp axis size
    d_ff: int = 64
    n_layers: int = 1
    causal: bool = True
    capacity_factor: float = 2.0
    aux_coef: float = 0.01
    # 'xla': ring attention, dense hop blocks
    # 'pallas': ring attention, flash-kernel hops (custom-VJP ring
    #   backward: a second KV rotation accumulating dk/dv)
    # 'ulysses-pallas': Ulysses all_to_all + differentiable flash kernel
    #   (needs n_heads % sp_size == 0)
    # all three are trainable
    attn_impl: str = "xla"
    # forward/backward arithmetic dtype; master params, the loss, and
    # the SGD update stay float32 (standard mixed precision: the cast
    # sits inside the loss, so value_and_grad returns f32 grads)
    compute_dtype: str = "float32"

    @property
    def d_head(self) -> int:
        if self.d_model % self.n_heads:
            raise ValueError(
                f"d_model {self.d_model} not divisible by n_heads {self.n_heads}"
            )
        return self.d_model // self.n_heads


def init_params(seed: int, cfg: TransformerConfig) -> dict:
    """Parameter pytree for ``cfg``; expert leaves have a leading
    (n_experts,) axis — the dimension sharded over "dp"."""
    rng = np.random.default_rng(seed)
    d, f, e = cfg.d_model, cfg.d_ff, cfg.n_experts

    def dense(*shape, scale=None):
        scale = scale if scale is not None else 1.0 / np.sqrt(shape[0])
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) * scale
        )

    layers = []
    for _ in range(cfg.n_layers):
        layers.append(
            {
                "wq": dense(d, d),
                "wk": dense(d, d),
                "wv": dense(d, d),
                "wo": dense(d, d),
                "ln1": jnp.ones((d,), jnp.float32),
                "ln2": jnp.ones((d,), jnp.float32),
                "gate": dense(d, e, scale=0.02),
                "w_in": dense(e, d, f, scale=1.0 / np.sqrt(d)),
                "w_out": dense(e, f, d, scale=1.0 / np.sqrt(f)),
            }
        )
    return {"layers": layers}


EXPERT_LEAVES = ("w_in", "w_out")  # the leaves sharded over "dp"
#: every per-layer parameter name (param_spec and param_spec_pp build
#: their spec pytrees from this one list so they can never drift)
LAYER_LEAVES = ("wq", "wk", "wv", "wo", "ln1", "ln2",
                "gate", "w_in", "w_out")


def _is_expert_leaf(path) -> bool:
    return any(getattr(k, "key", None) in EXPERT_LEAVES for k in path)


def nonexpert_size(tree) -> int:
    """Total element count of the NON-expert (replicated) leaves — the
    population the ZeRO path flattens into one dp-sharded vector."""
    total = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        if not _is_expert_leaf(path):
            total += int(np.prod(np.shape(leaf)))
    return total


def expert_leaves(tree) -> list:
    """The expert leaves of ``tree`` in tree-flatten order (the order
    :func:`pack_nonexpert`/:func:`unpack_nonexpert` also walk) — the
    dp-sharded complement of the packed flat vector."""
    return [
        leaf
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree)
        if _is_expert_leaf(path)
    ]


def pack_nonexpert(tree, pad_to: int | None = None):
    """Flatten every non-expert leaf of ``tree`` into ONE 1-D f32 vector
    (tree-flatten order), zero-padded to ``pad_to`` elements — the layout
    the ZeRO path reduce-scatters over "dp" and the fused optimizer
    updates as per-rank shards.  Zero padding is exact for the gradient
    math: padded slots carry zero gradient and zero moments forever."""
    flats = [
        jnp.ravel(leaf)
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree)
        if not _is_expert_leaf(path)
    ]
    flat = jnp.concatenate(flats) if flats else jnp.zeros((0,), jnp.float32)
    if pad_to is not None:
        if pad_to < flat.size:
            raise ValueError(
                f"pad_to {pad_to} smaller than packed size {flat.size}"
            )
        if pad_to > flat.size:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad_to - flat.size,), flat.dtype)]
            )
    return flat


def unpack_nonexpert(flat, experts: list, like):
    """Inverse of :func:`pack_nonexpert` + :func:`expert_leaves`:
    rebuild a full parameter tree shaped ``like``, non-expert leaves
    sliced out of ``flat`` (padding tail ignored), expert leaves taken
    from the ``experts`` list in order."""
    offset = 0
    exp_iter = iter(experts)

    def fill(path, leaf):
        nonlocal offset
        if _is_expert_leaf(path):
            return next(exp_iter)
        n = int(np.prod(np.shape(leaf)))
        seg = flat[offset:offset + n].reshape(np.shape(leaf))
        offset += n
        return seg

    out = jax.tree_util.tree_map_with_path(fill, like)
    rest = list(exp_iter)
    if rest:
        raise ValueError(f"{len(rest)} expert leaves left over in unpack")
    return out


def param_spec(cfg: TransformerConfig, dp: str = "dp") -> dict:
    """PartitionSpec pytree: expert leaves sharded over ``dp`` on their
    expert axis, everything else replicated. Built structurally from the
    config (materializing a throwaway parameter set just for its tree
    shape would cost RNG time and device memory)."""
    layer = {
        name: P(dp) if name in EXPERT_LEAVES else P()
        for name in LAYER_LEAVES
    }
    return {"layers": [dict(layer) for _ in range(cfg.n_layers)]}


def _rms_norm(x, scale):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-6) * scale


def _block(p, x, cfg: TransformerConfig, sp: str, dp: str):
    """One attention + MoE block on a local (B_loc, S_loc, d) shard.
    Returns (new_x, aux_loss)."""
    B, S, d = x.shape
    H, Dh = cfg.n_heads, cfg.d_head

    h = _rms_norm(x, p["ln1"])
    q = (h @ p["wq"]).reshape(B, S, H, Dh)
    k = (h @ p["wk"]).reshape(B, S, H, Dh)
    v = (h @ p["wv"]).reshape(B, S, H, Dh)
    if cfg.attn_impl == "ulysses-pallas":
        from tpuscratch.parallel.ulysses import ulysses_attention

        seq_attn = lambda qb, kb, vb: ulysses_attention(  # noqa: E731
            qb, kb, vb, sp, causal=cfg.causal, impl="pallas"
        )
    else:
        seq_attn = lambda qb, kb, vb: ring_attention(  # noqa: E731
            qb, kb, vb, sp, causal=cfg.causal, impl=cfg.attn_impl
        )
    attn = jax.vmap(seq_attn)(q, k, v)
    x = x + attn.reshape(B, S, d) @ p["wo"]

    h = _rms_norm(x, p["ln2"])
    tokens = h.reshape(B * S, d)
    moe, aux = expert_parallel_ffn(
        tokens, p["gate"], p["w_in"], p["w_out"], dp,
        capacity_factor=cfg.capacity_factor,
    )
    return x + moe.reshape(B, S, d), aux


def model_apply(params, x, cfg: TransformerConfig, sp: str = "sp", dp: str = "dp"):
    """Forward over a local shard: x (B_loc, S_loc, d) -> (out, aux)."""
    aux_total = jnp.float32(0.0)
    for p in params["layers"]:
        x, aux = _block(p, x, cfg, sp, dp)
        aux_total = aux_total + aux
    return x, aux_total


def _loss(params, x, y, cfg: TransformerConfig, sp: str, dp: str):
    cd = jnp.dtype(cfg.compute_dtype)
    if cd != jnp.float32:
        params = jax.tree.map(lambda w: w.astype(cd), params)
        x = x.astype(cd)
    out, aux = model_apply(params, x, cfg, sp, dp)
    # the error and the objective are f32 regardless of compute dtype
    mse = jnp.mean(jnp.square(out.astype(jnp.float32) - y.astype(jnp.float32)))
    aux = jnp.asarray(aux, jnp.float32)
    # identical on every rank: the global objective, not a local one
    return lax.pmean(mse + cfg.aux_coef * aux, (dp, sp))


def _grad_reduce(grads, dp: str, sp: str):
    """Combine the per-copy gradients into the logical gradient.

    Every one of the n = |dp|*|sp| ranks seeds its own replica of the
    pmean'd loss with cotangent 1, and the collective transposes (ring
    ppermute, expert all_to_all) route each seed's cross-rank terms to
    the copy that produced them — so summing a leaf's grads over its
    copy axes counts every seed exactly once per copy-set, i.e. n times
    the logical gradient. The rule is therefore uniform:
    psum over the leaf's copy axes, divided by n. Expert leaves have
    copies across "sp" only (across "dp" they are DIFFERENT experts —
    their single copy still receives all n seeds via the all_to_all
    transpose); everything else has copies across both axes. Validated
    by the sharding-invariance test (1x1 == 2x1 == 1x4 == 2x4 meshes,
    tests/test_models.py)."""
    n = lax.axis_size(dp) * lax.axis_size(sp)

    def reduce_leaf(path, g):
        axes = (sp,) if _is_expert_leaf(path) else (dp, sp)
        return lax.psum(g, axes) / n

    return jax.tree_util.tree_map_with_path(reduce_leaf, grads)


def _grad_norm(grads, dp: str):
    """Global L2 norm of the REDUCED (logical) gradient.  Non-expert
    leaves are replicated after ``_grad_reduce``, so their local square
    sum already is the logical one; expert leaves live dp-sharded (each
    rank holds only its experts), so their square sums psum over dp.
    The result is identical on every rank — the trainer's per-step
    health signal (loss says whether learning works, grad-norm says
    whether it is about to stop working)."""

    def leaf_sq(path, g):
        s = jnp.sum(jnp.square(g.astype(jnp.float32)))
        return lax.psum(s, dp) if _is_expert_leaf(path) else s

    sq = jax.tree_util.tree_map_with_path(leaf_sq, grads)
    return jnp.sqrt(sum(jax.tree_util.tree_leaves(sq)))


def _apply_guard(loss, gnorm, grads, ref_loss, clip_norm, spike_factor,
                 dp: str, sp: str, extra_axes: tuple = ()):
    """Device-side health guard (the compiled half of ``ft.guards``):

    - finiteness: the local ``isfinite(loss) & isfinite(gnorm)`` flag
      (a NaN/Inf in ANY gradient leaf propagates into the global grad
      norm, so the pair covers the whole tree) reduced over ALL mesh
      axes through ``comm.collectives`` — every rank agrees, so the
      skip-select below cannot diverge the replicas;
    - loss spike: ``loss > spike_factor * ref_loss`` against the
      caller-fed reference loss (the previous chunk's; a non-finite or
      non-positive reference disables the check — the first chunk);
    - clip: gradients above ``clip_norm`` are rescaled in-program.

    Returns ``(ok, status, grads)``: ``ok`` gates the update
    (skip-step = params pass through unchanged), ``status`` is the ONE
    extra int32 scalar output (0 ok / 1 clipped / 2 skipped).
    ``extra_axes`` extends the finiteness agreement to further mesh
    axes (the pipeline plan's stage axis) so the skip-select cannot
    diverge replicas on any axis of the mesh."""
    from tpuscratch.comm import collectives as C

    finite = jnp.isfinite(loss) & jnp.isfinite(gnorm)
    finite = C.allreduce_min(
        finite.astype(jnp.int32), (dp, sp) + tuple(extra_axes)
    ) > 0
    spiked = (
        jnp.isfinite(ref_loss) & (ref_loss > 0)
        & (loss > jnp.float32(spike_factor) * ref_loss)
    )
    ok = finite & ~spiked
    clip = finite & (gnorm > clip_norm)
    scale = jnp.where(clip, jnp.float32(clip_norm) / jnp.maximum(gnorm, 1e-30),
                      jnp.float32(1.0))
    grads = jax.tree.map(lambda g: g * scale, grads)
    status = jnp.where(ok, jnp.where(clip, 1, 0), 2).astype(jnp.int32)
    return ok, status, grads


def train_step_fn(cfg: TransformerConfig, lr: float = 1e-2,
                  sp: str = "sp", dp: str = "dp",
                  with_grad_norm: bool = False,
                  guard: tuple | None = None):
    """The shard_map body: (params, x, y) -> (new_params, loss) — or
    (new_params, loss, grad_norm) when ``with_grad_norm`` (the obs
    trainer hook; a separate trace, so the uninstrumented program is
    byte-identical to before).

    ``guard=(clip_norm, spike_factor)`` folds the device-side health
    guard in (see :func:`_apply_guard`): the body becomes
    (params, x, y, ref_loss) -> (new_params, loss, grad_norm, status)
    with a skipped step passing params through unchanged.  ``guard=None``
    returns EXACTLY the pre-guard body, so uninstrumented programs are
    unchanged."""
    if guard is None:
        def step(params, x, y):
            loss, grads = jax.value_and_grad(_loss)(params, x, y, cfg, sp, dp)
            grads = _grad_reduce(grads, dp, sp)
            new_params = jax.tree.map(lambda w, g: w - lr * g, params, grads)
            if with_grad_norm:
                return new_params, loss, _grad_norm(grads, dp)
            return new_params, loss

        return step

    clip_norm, spike_factor = guard

    def guarded_step(params, x, y, ref_loss):
        loss, grads = jax.value_and_grad(_loss)(params, x, y, cfg, sp, dp)
        grads = _grad_reduce(grads, dp, sp)
        gnorm = _grad_norm(grads, dp)
        ok, status, grads = _apply_guard(
            loss, gnorm, grads, ref_loss, clip_norm, spike_factor, dp, sp
        )
        new_params = jax.tree.map(
            lambda w, g: jnp.where(ok, w - lr * g, w), params, grads
        )
        return new_params, loss, gnorm, status

    return guarded_step


def init_adam_state(params) -> dict:
    """Fresh Adam moments, laid out EXACTLY like the params — expert-leaf
    moments carry the leading (n_experts,) axis and shard over ``dp``
    with their leaves (optimizer-state sharding: each device stores the
    first/second moments only for the expert slices it owns — the
    ZeRO-flavored placement a replicated optimizer would waste
    dp-times the memory on)."""
    return {
        "mu": jax.tree.map(jnp.zeros_like, params),
        "nu": jax.tree.map(jnp.zeros_like, params),
        "t": jnp.zeros((), jnp.int32),
    }


def adam_state_spec(cfg: TransformerConfig, dp: str = "dp") -> dict:
    """PartitionSpec pytree for :func:`init_adam_state`'s output."""
    return {
        "mu": param_spec(cfg, dp),
        "nu": param_spec(cfg, dp),
        "t": P(),
    }


def adam_alpha(t, lr, b1, b2):
    """Bias-corrected Adam step size at (1-based) step ``t`` — scalar,
    traced once, shared by every update variant (tree-mapped, fused
    kernel, ZeRO shard)."""
    tf = t.astype(jnp.float32)
    return lr * jnp.sqrt(1.0 - b2**tf) / (1.0 - b1**tf)


def _adam_apply(params, mu, nu, grads, alpha, b1, b2, eps):
    """One elementwise Adam application over matching pytrees with the
    step size ``alpha`` already bias-corrected: returns
    (new_params, new_mu, new_nu).  Sharding-agnostic — the ZeRO path
    runs it on dp-local expert leaves, the replicated path on the full
    tree."""
    mu = jax.tree.map(lambda m, g: b1 * m + (1.0 - b1) * g, mu, grads)
    nu = jax.tree.map(lambda v, g: b2 * v + (1.0 - b2) * g * g, nu, grads)
    new_params = jax.tree.map(
        lambda w, m, v: w - alpha * m / (jnp.sqrt(v) + eps),
        params, mu, nu,
    )
    return new_params, mu, nu


def _adam_update(params, opt, grads, lr, b1, b2, eps):
    """The shared Adam math (elementwise, sharding-agnostic): returns
    (new_params, new_opt).  Bias correction is folded into the step
    size (scalar, traced once)."""
    t = opt["t"] + 1
    new_params, mu, nu = _adam_apply(
        params, opt["mu"], opt["nu"], grads, adam_alpha(t, lr, b1, b2),
        b1, b2, eps,
    )
    return new_params, {"mu": mu, "nu": nu, "t": t}


def train_step_adam_fn(cfg: TransformerConfig, lr: float = 1e-3,
                       b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                       sp: str = "sp", dp: str = "dp",
                       with_grad_norm: bool = False,
                       guard: tuple | None = None):
    """The shard_map body: (params, opt, x, y) -> (params, opt, loss)
    (+ grad_norm when ``with_grad_norm``).

    Adam is elementwise, so the per-shard update composes with any
    sharding as long as the moments shard like the params (they do, by
    construction); the cross-rank math is all in ``_grad_reduce``.

    ``guard=(clip_norm, spike_factor)``: same contract as
    :func:`train_step_fn` — (params, opt, x, y, ref_loss) ->
    (params, opt, loss, grad_norm, status); a skipped step freezes the
    MOMENTS and the step count along with the params (a half-applied
    optimizer state would corrupt the bias correction)."""
    if guard is None:
        def step(params, opt, x, y):
            loss, grads = jax.value_and_grad(_loss)(params, x, y, cfg, sp, dp)
            grads = _grad_reduce(grads, dp, sp)
            new_params, new_opt = _adam_update(params, opt, grads, lr, b1, b2,
                                               eps)
            if with_grad_norm:
                return new_params, new_opt, loss, _grad_norm(grads, dp)
            return new_params, new_opt, loss

        return step

    clip_norm, spike_factor = guard

    def guarded_step(params, opt, x, y, ref_loss):
        loss, grads = jax.value_and_grad(_loss)(params, x, y, cfg, sp, dp)
        grads = _grad_reduce(grads, dp, sp)
        gnorm = _grad_norm(grads, dp)
        ok, status, grads = _apply_guard(
            loss, gnorm, grads, ref_loss, clip_norm, spike_factor, dp, sp
        )
        up_params, up_opt = _adam_update(params, opt, grads, lr, b1, b2, eps)
        sel = lambda new, cur: jax.tree.map(  # noqa: E731
            lambda a, b: jnp.where(ok, a, b), new, cur
        )
        return sel(up_params, params), sel(up_opt, opt), loss, gnorm, status

    return guarded_step


def train_step_adam(
    mesh: Mesh,
    cfg: TransformerConfig,
    lr: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    dp: str = "dp",
    sp: str = "sp",
    with_grad_norm: bool = False,
    counter=None,
    guard: tuple | None = None,
):
    """:func:`train_step` with Adam: jit'd fn(params, opt_state, x, y)
    -> (params, opt_state, loss); ``opt_state`` from
    :func:`init_adam_state`, moments sharded like their params.
    ``with_grad_norm`` appends the replicated grad-norm scalar;
    ``counter`` (an ``obs.CompileCounter``) counts traces of the body —
    the trainer's recompile detector.  ``guard=(clip_norm,
    spike_factor)`` builds the guarded variant — fn(params, opt, x, y,
    ref_loss) -> (params, opt, loss, grad_norm, status); ``guard=None``
    leaves the program unchanged."""
    _validate_step_config(mesh, cfg, dp, sp)
    pspec = param_spec(cfg, dp)
    ospec = adam_state_spec(cfg, dp)
    body = train_step_adam_fn(cfg, lr, b1, b2, eps, sp=sp, dp=dp,
                              with_grad_norm=with_grad_norm, guard=guard)
    if counter is not None:
        body = counter.wrap(body)
    if guard is not None:
        in_specs = (pspec, ospec, P(dp, sp), P(dp, sp), P())
        out = (pspec, ospec, P(), P(), P())
    else:
        in_specs = (pspec, ospec, P(dp, sp), P(dp, sp))
        out = (pspec, ospec, P(), P()) if with_grad_norm else (pspec, ospec, P())
    return run_spmd(
        mesh,
        body,
        in_specs,
        out,
    )


def _validate_step_config(mesh, cfg: TransformerConfig, dp: str, sp: str):
    n_dp = mesh.shape[dp]
    if cfg.n_experts % n_dp:
        raise ValueError(
            f"n_experts {cfg.n_experts} not divisible by dp size {n_dp}"
        )
    if cfg.attn_impl not in ("xla", "pallas", "ulysses-pallas"):
        raise ValueError(
            f"unknown attn_impl {cfg.attn_impl!r}: "
            "'xla' | 'pallas' | 'ulysses-pallas'"
        )
    if cfg.attn_impl == "ulysses-pallas" and cfg.n_heads % mesh.shape[sp]:
        raise ValueError(
            f"ulysses-pallas needs n_heads {cfg.n_heads} divisible by "
            f"sp size {mesh.shape[sp]}"
        )


def stack_layers(params: dict) -> dict:
    """Stack the per-layer dicts into one dict of (n_layers, ...) arrays
    — the layout the stage axis shards (leading axis = layer = stage
    ownership)."""
    layers = params["layers"]
    return {
        "layers": {
            k: jnp.stack([p[k] for p in layers]) for k in layers[0]
        }
    }


def unstack_layers(stacked: dict) -> dict:
    """Inverse of :func:`stack_layers`."""
    sl = stacked["layers"]
    n = next(iter(sl.values())).shape[0]
    return {"layers": [{k: sl[k][i] for k in sl} for i in range(n)]}


def param_spec_pp(cfg: TransformerConfig, stage: str = "stage",
                  dp: str = "dp") -> dict:
    """PartitionSpec pytree for :func:`stack_layers`' output: every leaf
    sharded over ``stage`` on the layer axis; expert leaves additionally
    over ``dp`` on their expert axis."""
    return {
        "layers": {
            name: P(stage, dp) if name in EXPERT_LEAVES else P(stage)
            for name in LAYER_LEAVES
        }
    }


def _pp_loss_fn(cfg: TransformerConfig, n_micro: int, sp: str, dp: str,
                stage: str):
    """The 3-axis pipeline loss both step builders share: GPipe
    microbatching over ``stage`` wrapping the dp x sp block (ring
    attention over sp, expert MoE over dp) — all four strategies
    composed in ONE program.

    Each stage rank owns ``n_layers / |stage|`` consecutive layers
    (stacked leaves, :func:`param_spec_pp`); the local batch splits into
    ``n_micro`` microbatches streaming through the open ppermute chain
    on the GPipe schedule (parallel/pipeline.py); every tick every stage
    runs its layers' full dp x sp block.  The MoE aux loss accumulates
    per (tick, stage) masked by schedule validity and is averaged over
    microbatches, so its scale matches the sequential step's.  Gradient
    reduction is :func:`_grad_reduce` unchanged: ``stage`` is an
    ownership axis (different layers), never a copy axis — the same
    reason expert leaves skip the ``dp`` psum.  Reference lineage: the
    lock-step stage circulation of mpi4.cpp:24-44, made trainable.
    """

    def loss_fn(stacked, x, y):
        cd = jnp.dtype(cfg.compute_dtype)
        if cd != jnp.float32:
            stacked = jax.tree.map(lambda w: w.astype(cd), stacked)
            x = x.astype(cd)
        sl = stacked["layers"]
        ls = next(iter(sl.values())).shape[0]  # layers per stage
        B, S, d = x.shape
        M = n_micro
        if B % M:
            raise ValueError(f"local batch {B} not divisible by {M} microbatches")
        micro = x.reshape(M, B // M, S, d)

        def stage_apply(act):
            aux = jnp.float32(0.0)
            for i in range(ls):
                p = {k: sl[k][i] for k in sl}
                act, a = _block(p, act, cfg, sp, dp)
                aux = aux + a
            return act, aux

        # the ONE GPipe schedule implementation (parallel/pipeline.py)
        # — the same tick loop pipeline_apply and the pipeline bench run
        out, aux_acc = gpipe_scan(stage_apply, micro, stage)
        out = out.reshape(B, S, d)
        aux = aux_acc / M
        mse = jnp.mean(
            jnp.square(out.astype(jnp.float32) - y.astype(jnp.float32))
        )
        return lax.pmean(mse + cfg.aux_coef * aux, (dp, sp))

    return loss_fn


def train_step_pp_fn(cfg: TransformerConfig, lr: float = 1e-2,
                     n_micro: int = 2, sp: str = "sp", dp: str = "dp",
                     stage: str = "stage"):
    """The 3-axis shard_map body with SGD: (stacked, x, y) ->
    (stacked, loss).  See :func:`_pp_loss_fn` for the pipeline."""
    loss_fn = _pp_loss_fn(cfg, n_micro, sp, dp, stage)

    def step(stacked, x, y):
        loss, grads = _pp_loss_and_grads(loss_fn, stacked, x, y, dp, sp,
                                         stage)
        new_params = jax.tree.map(lambda w, g: w - lr * g, stacked, grads)
        return new_params, loss

    return step


def _validate_pp(mesh, cfg: TransformerConfig, dp: str, sp: str,
                 stage: str):
    """The pipeline step builders' shared preconditions."""
    _validate_step_config(mesh, cfg, dp, sp)
    if cfg.n_layers % mesh.shape[stage]:
        raise ValueError(
            f"n_layers {cfg.n_layers} not divisible by stage size "
            f"{mesh.shape[stage]}"
        )


def _pp_loss_and_grads(loss_fn, stacked, x, y, dp, sp, stage):
    """value_and_grad + the 3-axis reduction: :func:`_grad_reduce` for
    the dp/sp copy axes, then ÷|stage| — every stage rank seeds its own
    replica of the (stage-replicated) loss and the stage-psum/
    ppermute-chain transposes deliver ALL |stage| seeds to every leaf, a
    uniform overcount on top of the dp x sp accounting (caught by the
    dryrun's bit-exactness gate)."""
    loss, grads = jax.value_and_grad(loss_fn)(stacked, x, y)
    grads = _grad_reduce(grads, dp, sp)
    n_stage = lax.axis_size(stage)
    if n_stage > 1:
        grads = jax.tree.map(lambda g: g / n_stage, grads)
    return loss, grads


def train_step_pp_adam_fn(cfg: TransformerConfig, lr: float = 1e-3,
                          b1: float = 0.9, b2: float = 0.999,
                          eps: float = 1e-8, n_micro: int = 2,
                          sp: str = "sp", dp: str = "dp",
                          stage: str = "stage"):
    """The 3-axis body with Adam: (stacked, opt, x, y) -> (stacked, opt,
    loss).  Moments are stacked exactly like the params (stage-sharded,
    expert leaves also over dp), so the elementwise update composes with
    the 3-axis sharding the same way the dp x sp Adam does."""
    loss_fn = _pp_loss_fn(cfg, n_micro, sp, dp, stage)

    def step(stacked, opt, x, y):
        loss, grads = _pp_loss_and_grads(loss_fn, stacked, x, y, dp, sp,
                                         stage)
        new_params, new_opt = _adam_update(stacked, opt, grads, lr, b1, b2,
                                           eps)
        return new_params, new_opt, loss

    return step


def adam_state_spec_pp(cfg: TransformerConfig, stage: str = "stage",
                       dp: str = "dp") -> dict:
    """PartitionSpec pytree for the stacked Adam moments."""
    return {
        "mu": param_spec_pp(cfg, stage, dp),
        "nu": param_spec_pp(cfg, stage, dp),
        "t": P(),
    }


def train_step_pp_adam(
    mesh: Mesh,
    cfg: TransformerConfig,
    lr: float = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    n_micro: int = 2,
    dp: str = "dp",
    sp: str = "sp",
    stage: str = "stage",
):
    """:func:`train_step_pp` with Adam: jit'd fn(stacked, opt, x, y) ->
    (stacked, opt, loss); ``opt`` from :func:`init_adam_state` applied
    to the STACKED params.  The MoE aux term depends on ``n_micro``
    (see :func:`train_step_pp`)."""
    _validate_pp(mesh, cfg, dp, sp, stage)
    pspec = param_spec_pp(cfg, stage, dp)
    ospec = adam_state_spec_pp(cfg, stage, dp)
    return run_spmd(
        mesh,
        train_step_pp_adam_fn(cfg, lr, b1, b2, eps, n_micro, sp=sp, dp=dp,
                              stage=stage),
        (pspec, ospec, P(dp, sp), P(dp, sp)),
        (pspec, ospec, P()),
    )


def train_step_pp(
    mesh: Mesh,
    cfg: TransformerConfig,
    lr: float = 1e-2,
    n_micro: int = 2,
    dp: str = "dp",
    sp: str = "sp",
    stage: str = "stage",
):
    """Compiled 3-axis training step over ``mesh`` (dp x sp x stage):
    jit'd fn(stacked_params, x, y) -> (stacked_params, loss) with the
    stacked layout from :func:`stack_layers` sharded by
    :func:`param_spec_pp` and x, y (batch, seq, d_model) sharded
    P(dp, sp).

    Numerical note: the MoE load-balance aux term is averaged over
    microbatches, and because that loss is nonlinear in routing-group
    size, the ``n_micro > 1`` step is NOT bit-equivalent to the
    sequential (``n_micro == 1``) step — the aux value (and its
    gradient) depends on ``n_micro``, with drift growing as microbatches
    shrink. Compare losses across schedules at fixed ``n_micro`` only.
    """
    _validate_pp(mesh, cfg, dp, sp, stage)
    pspec = param_spec_pp(cfg, stage, dp)
    return run_spmd(
        mesh,
        train_step_pp_fn(cfg, lr, n_micro, sp=sp, dp=dp, stage=stage),
        (pspec, P(dp, sp), P(dp, sp)),
        (pspec, P()),
    )


def train_step(
    mesh: Mesh,
    cfg: TransformerConfig,
    lr: float = 1e-2,
    dp: str = "dp",
    sp: str = "sp",
    with_grad_norm: bool = False,
    counter=None,
    guard: tuple | None = None,
):
    """Compiled training step over ``mesh`` (axes ``dp`` x ``sp``).

    Returns jit'd fn(params, x, y) -> (new_params, loss) with x, y
    (batch, seq, d_model) sharded P(dp, sp) and params laid out by
    ``param_spec``. The full composed surface — ring attention over sp,
    expert all_to_all over dp, grad, psum totals, SGD — is ONE XLA
    program.  ``with_grad_norm`` appends the replicated grad-norm
    scalar to the outputs; ``counter`` (an ``obs.CompileCounter``)
    counts traces of the body, the trainer's recompile detector.

    ``guard=(clip_norm, spike_factor)`` builds the ft-guarded variant —
    fn(params, x, y, ref_loss) -> (params, loss, grad_norm, status),
    the finiteness/spike/clip guard folded into the SAME compiled
    program (see :func:`_apply_guard`); ``guard=None`` (the default)
    leaves the program unchanged.
    """
    _validate_step_config(mesh, cfg, dp, sp)
    pspec = param_spec(cfg, dp)
    body = train_step_fn(cfg, lr, sp=sp, dp=dp,
                         with_grad_norm=with_grad_norm, guard=guard)
    if counter is not None:
        body = counter.wrap(body)
    if guard is not None:
        in_specs = (pspec, P(dp, sp), P(dp, sp), P())
        out = (pspec, P(), P(), P())
    else:
        in_specs = (pspec, P(dp, sp), P(dp, sp))
        out = (pspec, P(), P()) if with_grad_norm else (pspec, P())
    return run_spmd(
        mesh,
        body,
        in_specs,
        out,
    )
