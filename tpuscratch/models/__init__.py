"""Model compositions built from the parallel/ops layers.

The reference is an MPI scratchpad with no model zoo (SURVEY.md §2) — its
"models" are the numbered SPMD programs, mirrored one-for-one in
examples/. This package holds the framework's composed demonstrations:
multiple parallelism families sharded over one mesh in a single compiled
training step (models.transformer), the thing the individual
parallel/* modules exist to make possible.
"""

from tpuscratch.models.transformer import (  # noqa: F401
    TransformerConfig,
    init_adam_state,
    init_params,
    model_apply,
    train_step,
    train_step_adam,
)
from tpuscratch.models.zero import (  # noqa: F401
    init_zero_adam_state,
    train_step_zero,
    zero_state_spec,
)
from tpuscratch.models.ssm import SSMConfig, ssm_block  # noqa: F401
from tpuscratch.models.ssm import init_params as init_ssm_params  # noqa: F401
from tpuscratch.models.trainer import TrainReport, train  # noqa: F401
