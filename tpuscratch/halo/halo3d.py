"""3D halo exchange and 7-point stencil — the flagship, one dimension up.

The reference's domain-decomposition library is strictly 2D
(/root/reference/stencil2d/stencil2D.h); real HPC stencils are mostly 3D.
This module extends the same plan-then-execute design to a 3D torus of
devices: per-face slab transfers compiled to single-hop ``ppermute``s
over a 3-axis mesh, MPI_PROC_NULL semantics on open boundaries, and a
7-point Jacobi update.

Lean by default: a 7-point stencil needs only the 6 FACE slabs, so the
default plan keeps the per-step collective count at 6 — the 2D library's
13-region taxonomy does not reappear. For 27-point stencils the full
26-neighbor plan (faces + 12 edges + 8 corners, ``neighbors=26``) is
available: every transfer is still one single-hop ``ppermute`` (an edge
or corner neighbor is one diagonal hop on the torus, exactly like the 2D
corners). Everything else carries over unchanged: ``CartTopology`` was
already N-dimensional, ``SubarraySpec`` rectangles are rank-agnostic,
and the send/halo region math is generic over any offset in {-1,0,1}^3.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.dtypes import SubarraySpec
from tpuscratch.runtime.mesh import make_mesh
from tpuscratch.runtime.topology import CartTopology

#: The 6 face offsets of a 3D cell, exchange-plan order.
FACES: tuple[tuple[int, int, int], ...] = (
    (-1, 0, 0), (1, 0, 0),
    (0, -1, 0), (0, 1, 0),
    (0, 0, -1), (0, 0, 1),
)

#: All 26 neighbor offsets: faces first (plan-order stability), then the
#: 12 edges, then the 8 corners.
OFFSETS26: tuple[tuple[int, int, int], ...] = FACES + tuple(
    sorted(
        (
            (dz, dy, dx)
            for dz in (-1, 0, 1)
            for dy in (-1, 0, 1)
            for dx in (-1, 0, 1)
            if (dz, dy, dx) != (0, 0, 0)
            and abs(dz) + abs(dy) + abs(dx) >= 2
        ),
        key=lambda d: (abs(d[0]) + abs(d[1]) + abs(d[2]), d),
    )
)


@dataclasses.dataclass(frozen=True)
class TileLayout3D:
    """One rank's 3D tile: core extent + ghost slab widths per axis."""

    core: tuple[int, int, int]
    halo: tuple[int, int, int] = (1, 1, 1)

    def __post_init__(self):
        object.__setattr__(self, "core", tuple(int(c) for c in self.core))
        object.__setattr__(self, "halo", tuple(int(h) for h in self.halo))
        if len(self.core) != 3 or len(self.halo) != 3:
            raise ValueError(f"need 3 extents, got {self.core}/{self.halo}")
        if any(c <= 0 for c in self.core) or any(h < 0 for h in self.halo):
            raise ValueError(f"bad layout {self.core}/{self.halo}")
        if any(h > c for h, c in zip(self.halo, self.core)):
            raise ValueError("halo deeper than core: neighbor slabs overlap")

    @property
    def padded_shape(self) -> tuple[int, int, int]:
        return tuple(c + 2 * h for c, h in zip(self.core, self.halo))

    def send_region(self, offset: Sequence[int]) -> SubarraySpec:
        """Core slab adjacent to face ``offset`` — what travels there."""
        starts, extents = [], []
        for c, h, o in zip(self.core, self.halo, offset):
            if o < 0:
                starts.append(h), extents.append(h)
            elif o > 0:
                starts.append(h + c - h), extents.append(h)
            else:
                starts.append(h), extents.append(c)
        return SubarraySpec(tuple(starts), tuple(extents))

    def halo_region(self, offset: Sequence[int]) -> SubarraySpec:
        """Ghost slab on face ``offset`` — where that neighbor's data lands."""
        starts, extents = [], []
        for c, h, o in zip(self.core, self.halo, offset):
            if o < 0:
                starts.append(0), extents.append(h)
            elif o > 0:
                starts.append(h + c), extents.append(h)
            else:
                starts.append(h), extents.append(c)
        return SubarraySpec(tuple(starts), tuple(extents))


@dataclasses.dataclass(frozen=True)
class Transfer3D:
    """One face's worth of the plan (mirrors halo.exchange.Transfer)."""

    offset: tuple[int, int, int]
    send: SubarraySpec
    recv: SubarraySpec
    perm: tuple[tuple[int, int], ...]
    has_sender: tuple[bool, ...]


@dataclasses.dataclass(frozen=True)
class HaloSpec3D:
    """Compiled-constant description of one 3D halo exchange.

    ``neighbors``: 6 (faces only — 7-point stencils) or 26 (faces +
    edges + corners — 27-point stencils)."""

    layout: TileLayout3D
    topology: CartTopology
    axes: tuple[str, str, str] = ("z", "row", "col")
    neighbors: int = 6

    def __post_init__(self):
        if self.topology.ndim != 3:
            raise ValueError("3D halo exchange requires a 3D topology")
        if self.neighbors not in (6, 26):
            raise ValueError("neighbors must be 6 or 26")

    def directions(self) -> tuple[tuple[int, int, int], ...]:
        return OFFSETS26 if self.neighbors == 26 else FACES

    def plan(self) -> tuple[Transfer3D, ...]:
        return _cached_plan3d(self.layout, self.topology, self.neighbors)


@functools.lru_cache(maxsize=None)
def _cached_plan3d(
    layout: TileLayout3D, topology: CartTopology, neighbors: int = 6
) -> tuple[Transfer3D, ...]:
    from tpuscratch import native

    directions = OFFSETS26 if neighbors == 26 else FACES
    if native.available() and native.has_plan3d():
        raw = native.build_plan3d(
            topology.dims, topology.periodic, layout.core, layout.halo,
            neighbors,
        )
        out = []
        for nat in raw:
            perm = tuple((int(a), int(b)) for a, b in nat["perm"])
            receivers = {dst for _, dst in perm}
            so, se = nat["send_rect"][:3], nat["send_rect"][3:]
            ro, re_ = nat["recv_rect"][:3], nat["recv_rect"][3:]
            out.append(
                Transfer3D(
                    offset=tuple(nat["offset"]),
                    send=SubarraySpec(tuple(so), tuple(se)),
                    recv=SubarraySpec(tuple(ro), tuple(re_)),
                    perm=perm,
                    has_sender=tuple(
                        r in receivers for r in topology.ranks()
                    ),
                )
            )
        return tuple(out)

    out = []
    for d in directions:
        flow = tuple(-x for x in d)  # data in my d halo was sent toward -d
        perm = tuple(topology.send_permutation(flow))
        receivers = {dst for _, dst in perm}
        out.append(
            Transfer3D(
                offset=d,
                send=layout.send_region(flow),
                recv=layout.halo_region(d),
                perm=perm,
                has_sender=tuple(r in receivers for r in topology.ranks()),
            )
        )
    return tuple(out)


def halo_exchange3d(tile: jnp.ndarray, spec: HaloSpec3D) -> jnp.ndarray:
    """Fill ``tile``'s ghost regions (6 face slabs, or all 26 regions for
    a ``neighbors=26`` spec) from its mesh neighbors (SPMD).

    Delegates to the 2D library's executor pair (halo/exchange.py
    ``halo_arrivals``/``halo_scatter``): the plan protocol
    (send/recv rects + permutation + sender mask) is rank-agnostic, so
    the same launch/mask/scatter code serves both dimensionalities — and
    the split arrivals/scatter API is available in 3D for overlap
    schemes, exactly as in 2D.
    """
    from tpuscratch.halo.exchange import halo_arrivals, halo_scatter

    return halo_scatter(tile, spec, halo_arrivals(tile, spec))


def halo_exchange3d_seq(tile: jnp.ndarray, spec: HaloSpec3D) -> jnp.ndarray:
    """Fill the FULL ghost shell — faces, edges, AND corners — with SIX
    ppermutes at ANY halo depth: the axis-sequential deep exchange.

    The 26-neighbor plan pays one collective per region (26 launches);
    here axis ``a``'s slab carries the PADDED extent of every
    already-exchanged axis, so edge and corner data arrives transitively
    (the z ghosts ride the y slabs, both ride the x slabs) in two or
    three single-axis hops — the classic axis-by-axis deep-halo trick,
    and the launch-count lever the s-step smoother amortizes: one
    6-ppermute exchange at depth ``s`` buys ``s`` sweeps where the
    per-sweep path pays 6 launches per sweep.

    Wire-byte accounting (``bench.weak_scaling.halo3d_traffic_per_chip``
    carries the same formula): slab bytes grow by the earlier axes'
    ghost bands, so a depth-``s`` exchange moves ``(1 + eps)`` times the
    bytes of ``s`` stacked face exchanges, ``eps = O(s / core)`` — the
    redundant-boundary trade the trapezoid scheme prices in.

    Open-boundary semantics differ from :func:`halo_exchange3d`: a rank
    with no sender gets ``ppermute`` ZEROS in that slab (the zero-ghost
    convention the solvers' padded embeds already rely on), not its
    prior ghost values.
    """
    lay = spec.layout
    topo = spec.topology
    core, halo = lay.core, lay.halo
    for a in range(3):
        h = halo[a]
        if h == 0:
            continue
        ext = []
        for b in range(3):
            if b < a:          # already exchanged: ship ghosts too
                ext.append(slice(0, core[b] + 2 * halo[b]))
            elif b > a:        # not yet exchanged: core only
                ext.append(slice(halo[b], halo[b] + core[b]))
            else:
                ext.append(None)
        for d_a in (-1, 1):    # the face whose ghosts this transfer fills
            flow = [0, 0, 0]
            flow[a] = -d_a     # data travels opposite the ghost face
            perm = tuple(topo.send_permutation(tuple(flow)))
            send_a = (slice(core[a], core[a] + h) if flow[a] > 0
                      else slice(h, 2 * h))
            recv_a = (slice(0, h) if d_a < 0
                      else slice(h + core[a], 2 * h + core[a]))
            src = tuple(send_a if b == a else ext[b] for b in range(3))
            dst = tuple(recv_a if b == a else ext[b] for b in range(3))
            if not perm:
                # fully open 1-wide axis: nobody sends anywhere — zero
                # the slab so the no-sender convention is uniform (a
                # multi-rank open axis gets the same zeros via
                # ppermute's non-receiver fill)
                arrived = jnp.zeros_like(tile[dst])
            elif len(perm) == topo.size and all(s == d for s, d in perm):
                arrived = tile[src]   # pure self-wrap: skip the collective
            else:
                arrived = lax.ppermute(tile[src], spec.axes, list(perm))
            tile = tile.at[dst].set(arrived)
    return tile


def seq_exchange_wire_bytes(spec: HaloSpec3D, itemsize: int = 4) -> float:
    """Analytic per-rank OFF-RANK wire bytes of one
    :func:`halo_exchange3d_seq` at this spec's halo depth — the exact
    number the obs ledger reads off the compiled program (tests assert
    equality).  Self-wrap pairs move nothing over the wire; open-edge
    ranks that send nowhere are averaged out exactly as
    ``bench.weak_scaling.halo_traffic_per_chip`` does for 2D."""
    lay = spec.layout
    topo = spec.topology
    core, halo = lay.core, lay.halo
    total = 0
    for a in range(3):
        h = halo[a]
        if h == 0:
            continue
        elems = h
        for b in range(3):
            if b < a:
                elems *= core[b] + 2 * halo[b]
            elif b > a:
                elems *= core[b]
        for d_a in (-1, 1):
            flow = [0, 0, 0]
            flow[a] = -d_a
            perm = tuple(topo.send_permutation(tuple(flow)))
            if len(perm) == topo.size and all(s == d for s, d in perm):
                continue
            total += elems * itemsize * sum(1 for s, d in perm if s != d)
    return total / topo.size


#: 7-point Jacobi default: equal face weights, no center term.
JACOBI7 = (1 / 6,) * 6 + (0.0,)


def stencil_step3d(
    tile: jnp.ndarray, spec: HaloSpec3D, coeffs=JACOBI7
) -> jnp.ndarray:
    """One exchange + stencil update.

    ``coeffs`` order: 7-point = FACES + (center,); 27-point = OFFSETS26 +
    (center,), which requires a ``neighbors=26`` spec (a face-only
    exchange never fills the edge/corner ghosts a 27-point stencil
    reads — rejected rather than silently wrong, like the 2D 9-point)."""
    if len(coeffs) not in (7, 27):
        raise ValueError(
            f"need 6+1 or 26+1 coeffs (FACES/OFFSETS26 + center), "
            f"got {len(coeffs)}"
        )
    if len(coeffs) == 27 and spec.neighbors != 26:
        raise ValueError(
            "27-point coeffs need a neighbors=26 HaloSpec3D: the face-only "
            "exchange never fills the edge/corner ghosts the stencil reads"
        )
    hz, hy, hx = spec.layout.halo
    if hz < 1 or hy < 1 or hx < 1:
        raise ValueError(
            f"3D stencils need halo >= 1 on every axis, got {spec.layout.halo}"
        )
    u = halo_exchange3d(tile, spec)
    cz, cy, cx = spec.layout.core
    core = lambda dz, dy, dx: lax.dynamic_slice(  # noqa: E731
        u, (hz + dz, hy + dy, hx + dx), (cz, cy, cx)
    )
    directions = OFFSETS26 if len(coeffs) == 27 else FACES
    new = coeffs[-1] * core(0, 0, 0)
    for (dz, dy, dx), w in zip(directions, coeffs[:-1]):
        new = new + w * core(dz, dy, dx)
    # rebuild by CONCATENATION, not dynamic_update_slice: an in-place core
    # update fused with overlapping shifted reads of the same buffer
    # miscompiles on XLA:CPU under shard_map (see halo/stencil.py rebuild())
    mid = jnp.concatenate(
        [u[hz:hz + cz, hy:hy + cy, :hx], new, u[hz:hz + cz, hy:hy + cy, hx + cx:]],
        axis=2,
    )
    slab = jnp.concatenate(
        [u[hz:hz + cz, :hy, :], mid, u[hz:hz + cz, hy + cy:, :]], axis=1
    )
    return jnp.concatenate([u[:hz], slab, u[hz + cz:]], axis=0)


def run_stencil3d(
    tile: jnp.ndarray, spec: HaloSpec3D, steps: int, coeffs=JACOBI7
) -> jnp.ndarray:
    """``steps`` exchange+compute iterations in one scanned program."""
    def step(t, _):
        return stencil_step3d(t, spec, coeffs), ()

    out, _ = lax.scan(step, tile, None, length=steps)
    return out


def stencil_step3d_compact(
    core: jnp.ndarray, spec: HaloSpec3D, coeffs=JACOBI7, compute: str = "xla"
) -> jnp.ndarray:
    """One exchange + stencil update carrying the CORE only — the fast
    path. The padded-carry step pays sequential full-tile
    dynamic_update_slices per exchange — each a full HBM pass; here the
    padded tile is materialized ONCE by nested concatenation of the
    arrival pieces around the core and the shifted reads fuse into the
    weighted sum. 7-point coeffs ship 6 face planes (edge/corner lines
    are zeros — never read); 27-point coeffs ship all 26 pieces (faces +
    12 edge lines + 8 corner points), each one diagonal ppermute hop —
    the core-carry twin of the padded 26-neighbor path, ``compute='xla'``
    only (the banded kernels are 7-point). Same numbers as the padded
    path (tests assert equality): on open boundaries the missing
    arrivals are ppermute zeros, which equal the zero ghosts the padded
    path keeps.
    """
    if len(coeffs) not in (7, 27):
        raise ValueError(
            f"need 6+1 or 26+1 coeffs (FACES/OFFSETS26 + center), "
            f"got {len(coeffs)}"
        )
    if len(coeffs) == 27 and compute != "xla":
        raise ValueError(
            f"27-point compact supports compute='xla' only, got {compute!r} "
            "(the banded Pallas kernels are 7-point)"
        )
    if spec.layout.halo != (1, 1, 1):
        raise ValueError(
            f"compact step supports halo (1,1,1), got {spec.layout.halo}"
        )
    topo = spec.topology
    axes = spec.axes
    cz, cy, cx = core.shape

    def arrival(d):
        """The sub-block my d-neighbor sends (its far side along -d) —
        a face plane, edge line, or corner point by d's rank."""
        flow = tuple(-x for x in d)
        take = tuple(
            slice(None) if d[a] == 0
            else (slice(-1, None) if flow[a] > 0 else slice(0, 1))
            for a in range(3)
        )
        if all(
            topo.dims[a] == 1 and topo.periodic[a]
            for a in range(3) if d[a]
        ):
            # every nonzero axis degenerate periodic: the neighbor is
            # myself, the ghost block is my own far block — skip the
            # collective (6 per-step self-ppermutes measured ~1.2
            # ms/step of pure launch overhead at 256x512x512 on v5e;
            # the 3D analogue of run_stencil_resident's self-wrap)
            return core[take]
        return lax.ppermute(
            core[take], axes, list(topo.send_permutation(flow))
        )

    if len(coeffs) == 27:
        return _compact27(core, coeffs, arrival)

    a_mz, a_pz, a_my, a_py, a_mx, a_px = (arrival(d) for d in FACES)

    if compute == "pallas-asm":
        # nothing assembled outside at all: the kernel's z-band pipeline
        # reads the core through clamped overlapping blocks and the six
        # arrival planes/strips through their own banded inputs — the
        # zpad build pass and the full-plane in-kernel concats are gone
        # (BASELINE row 9's named levers). Degenerate periodic y/x axes
        # pass None: the kernel reads its own block edges instead of
        # carry slices (a lane-dim carry slice costs ~a full HBM pass)
        from tpuscratch.ops.stencil_kernel import seven_point_assembled_pallas

        wrap_y = topo.dims[1] == 1 and topo.periodic[1]
        wrap_x = topo.dims[2] == 1 and topo.periodic[2]
        return seven_point_assembled_pallas(
            core, a_mz, a_pz,
            None if wrap_y else a_my, None if wrap_y else a_py,
            None if wrap_x else a_mx, None if wrap_x else a_px,
            (cz, cy, cx), tuple(coeffs),
        )

    if compute == "pallas-strips":
        # only the z axis is assembled outside; the y/x strips feed the
        # kernel directly — two fewer full-grid concat passes per step
        from tpuscratch.ops.stencil_kernel import seven_point_strips_pallas

        zpad = jnp.concatenate([a_mz, core, a_pz], axis=0)
        return seven_point_strips_pallas(
            zpad, a_my, a_py, a_mx, a_px, (cz, cy, cx), tuple(coeffs)
        )

    # ONE padded-tile materialization by nested concat (edge/corner lines
    # are zeros — a 7-point stencil never reads them), then the 7 shifted
    # reads fuse into the weighted sum
    mid = jnp.concatenate([a_mx, core, a_px], axis=2)        # (cz, cy, cx+2)
    zy = jnp.zeros((cz, 1, 1), core.dtype)
    north = jnp.concatenate([zy, a_my, zy], axis=2)          # (cz, 1, cx+2)
    south = jnp.concatenate([zy, a_py, zy], axis=2)
    mid = jnp.concatenate([north, mid, south], axis=1)       # (cz, cy+2, cx+2)
    zz = jnp.zeros((1, 1, cx + 2), core.dtype)
    zc = jnp.zeros((1, cy, 1), core.dtype)
    top = jnp.concatenate(
        [zz, jnp.concatenate([zc, a_mz, zc], axis=2), zz], axis=1
    )                                                        # (1, cy+2, cx+2)
    bot = jnp.concatenate(
        [zz, jnp.concatenate([zc, a_pz, zc], axis=2), zz], axis=1
    )
    u = jnp.concatenate([top, mid, bot], axis=0)             # padded tile

    if compute == "pallas":
        from tpuscratch.ops.stencil_kernel import seven_point_banded_pallas

        return seven_point_banded_pallas(u, (cz, cy, cx), tuple(coeffs))
    sl = lambda dz, dy, dx: u[  # noqa: E731
        1 + dz : 1 + dz + cz, 1 + dy : 1 + dy + cy, 1 + dx : 1 + dx + cx
    ]
    new = coeffs[6] * sl(0, 0, 0)
    for d, w in zip(FACES, coeffs[:6]):
        new = new + w * sl(*d)
    return new


def _compact27(core: jnp.ndarray, coeffs, arrival) -> jnp.ndarray:
    """27-point core-carry update: ONE padded tile from all 26 arrival
    pieces by nested concatenation (corner points seat the corners the
    7-point build zero-fills), then the 27 shifted reads fuse into the
    weighted sum."""
    cz, cy, cx = core.shape
    A = {d: arrival(d) for d in OFFSETS26}

    def rx(dz, dy):
        return jnp.concatenate(
            [A[(dz, dy, -1)], A[(dz, dy, 0)], A[(dz, dy, 1)]], axis=2
        )

    plane_m = jnp.concatenate([rx(-1, -1), rx(-1, 0), rx(-1, 1)], axis=1)
    plane_p = jnp.concatenate([rx(1, -1), rx(1, 0), rx(1, 1)], axis=1)
    mid = jnp.concatenate(
        [
            jnp.concatenate([A[(0, -1, -1)], A[(0, -1, 0)], A[(0, -1, 1)]], axis=2),
            jnp.concatenate([A[(0, 0, -1)], core, A[(0, 0, 1)]], axis=2),
            jnp.concatenate([A[(0, 1, -1)], A[(0, 1, 0)], A[(0, 1, 1)]], axis=2),
        ],
        axis=1,
    )
    u = jnp.concatenate([plane_m, mid, plane_p], axis=0)
    sl = lambda dz, dy, dx: u[  # noqa: E731
        1 + dz : 1 + dz + cz, 1 + dy : 1 + dy + cy, 1 + dx : 1 + dx + cx
    ]
    new = coeffs[-1] * sl(0, 0, 0)
    for d, w in zip(OFFSETS26, coeffs[:-1]):
        new = new + w * sl(*d)
    return new


def run_stencil3d_compact(
    core: jnp.ndarray,
    spec: HaloSpec3D,
    steps: int,
    coeffs=JACOBI7,
    compute: str = "xla",
) -> jnp.ndarray:
    """``steps`` compact iterations in one scanned program (core carry).

    ``compute='pallas'`` runs the 7-point sum as the banded VMEM kernel
    (ops.stencil_kernel.seven_point_banded_pallas) instead of XLA's
    fused slices.
    """
    def step(c, _):
        return stencil_step3d_compact(c, spec, coeffs, compute), ()

    out, _ = lax.scan(step, core, None, length=steps)
    return out


def run_stencil3d_stream(
    core: jnp.ndarray,
    spec: HaloSpec3D,
    steps: int,
    coeffs=JACOBI7,
    depth: int = 4,
    band: Optional[int] = None,
    nbuf: int = 2,
) -> jnp.ndarray:
    """``steps`` iterations via the deep-z streamed kernel: ``depth``
    substeps fold into each manual-DMA pass, dividing per-step HBM
    traffic by ``depth`` — the only lever past the measured ~330 GB/s
    DMA-fabric copy bound (ops/stencil_stream.py docstring carries the
    bound race).  z ghosts travel as (depth, cy, cx) slabs, one
    exchange per ``depth`` steps — the 2D ``deep:k`` trapezoid one
    dimension up (reference lineage: stencil2D.h:116-117, ghost depth
    as a parameter).

    y/x axes (round 5): a periodic size-1 axis self-wraps in-kernel
    (z-slab mode); a DISTRIBUTED (or open) y or x axis rides ghost
    strips — the neighbors' edge slabs with the diagonal neighbors'
    corner segments, the 26-neighbor transfer set at ghost depth
    ``depth`` — aged in-kernel alongside the window (7-point only; the
    27-point form keeps the z-slab requirement and falls back to
    ``compact-asm`` elsewhere).  Open boundaries get zero ghosts,
    matching the plain path's ppermute semantics.
    """
    from tpuscratch.ops.stencil_stream import seven_point_streamed_pallas

    if len(coeffs) not in (7, 27):
        raise ValueError(
            f"stream impl takes 7 or 27 coefficients, got {len(coeffs)}"
        )
    topo = spec.topology
    cz, cy, cx = core.shape
    wrap_y = topo.dims[1] == 1 and topo.periodic[1]
    wrap_x = topo.dims[2] == 1 and topo.periodic[2]
    if len(coeffs) == 27 and not (wrap_y and wrap_x):
        raise ValueError(
            "the 27-point stream impl needs a z-slab decomposition "
            f"(self-wrapping y and x), got dims={topo.dims} "
            f"periodic={topo.periodic}; use impl='compact-asm' for "
            "distributed y/x axes"
        )
    if jax.default_backend() == "tpu" and (cy < 8 or cx < 128):
        # chip rule the kernel's module docstring states (and until now
        # only the multigrid chooser gated on): plane extents below the
        # (8, 128) vector-tile pass the CPU interpreter but are a Mosaic
        # remote-compile DNF on silicon.  Mirror nine_point_streamed_2d's
        # H % 8 guard — but here the compact per-step path serves any
        # extent with identical semantics, so fall back instead of
        # raising (ADVICE r5).  Compute stays 'xla' — the banded Pallas
        # kernels block the same sub-tile planes, so they are not a safe
        # harbor (the multigrid chooser makes the same call for its
        # small coarse levels).
        return run_stencil3d_compact(core, spec, steps, coeffs,
                                     compute="xla")

    def gather(block, off):
        # the off-neighbor's block: local when the permutation is pure
        # self-wrap (self-ppermutes cost ~1.2 ms/step of launch
        # overhead, BASELINE row 9), zeros when nobody sends (fully
        # open), else a diagonal-capable ppermute with zero-fill at
        # open edges (the MPI_PROC_NULL analogue)
        pairs = list(topo.send_permutation(off))
        if not pairs:
            return jnp.zeros_like(block)
        if len(pairs) == topo.size and all(s == d for s, d in pairs):
            return block
        return lax.ppermute(block, spec.axes, pairs)

    def strip_z(block_top, block_mid, block_bot, off_yx):
        """A ghost strip spanning global planes [-d, cz+d): the
        off_yx-neighbor's mid block plus the z-diagonal neighbors'
        corner segments."""
        dy, dx = off_yx
        return jnp.concatenate([
            gather(block_top, (1, dy, dx)),
            gather(block_mid, (0, dy, dx)),
            gather(block_bot, (-1, dy, dx)),
        ], axis=0)

    def open_flags():
        # per-rank traced flags [z-, z+, y-, y+, x-, x+]: an OPEN
        # physical end must re-impose its zero ghosts every folded
        # substep (shard_map traces one program for every rank, so
        # this cannot be a static property)
        if all(topo.periodic):
            return None
        parts = []
        for axis in range(3):
            if topo.periodic[axis]:
                parts += [jnp.zeros((), jnp.int32)] * 2
            elif topo.dims[axis] == 1:
                parts += [jnp.ones((), jnp.int32)] * 2
            else:
                rc = lax.axis_index(spec.axes[axis])
                parts += [(rc == 0).astype(jnp.int32),
                          (rc == topo.dims[axis] - 1).astype(jnp.int32)]
        return jnp.stack(parts)

    flags = open_flags()

    def pass_fn(c, d):
        a_mz = gather(c[cz - d :], (1, 0, 0))
        a_pz = gather(c[:d], (-1, 0, 0))
        gy = gx = gc = None
        if not wrap_y:
            # [plus | minus] rows: south neighbors' top d rows, then
            # north neighbors' bottom d rows, each z-extended
            gy = jnp.concatenate([
                strip_z(c[cz - d :, :d, :], c[:, :d, :], c[:d, :d, :],
                        (-1, 0)),
                strip_z(c[cz - d :, cy - d :, :], c[:, cy - d :, :],
                        c[:d, cy - d :, :], (1, 0)),
            ], axis=1)
        if not wrap_x:
            gx = jnp.concatenate([
                strip_z(c[cz - d :, :, :d], c[:, :, :d], c[:d, :, :d],
                        (0, -1)),
                strip_z(c[cz - d :, :, cx - d :], c[:, :, cx - d :],
                        c[:d, :, cx - d :], (0, 1)),
            ], axis=2)
        if not wrap_y and not wrap_x:
            # xy-corner strip: quadrants [y-plus | y-minus] x
            # [x-plus | x-minus], each from the matching diagonal
            # neighbor's opposite corner block, z-extended
            def quad(oy, ox):
                ys = slice(0, d) if oy == -1 else slice(cy - d, cy)
                xs = slice(0, d) if ox == -1 else slice(cx - d, cx)
                return strip_z(
                    c[cz - d :, ys, xs], c[:, ys, xs], c[:d, ys, xs],
                    (oy, ox),
                )

            gc = jnp.concatenate([
                jnp.concatenate([quad(-1, -1), quad(-1, 1)], axis=2),
                jnp.concatenate([quad(1, -1), quad(1, 1)], axis=2),
            ], axis=1)
        return seven_point_streamed_pallas(
            c, a_mz, a_pz, (cz, cy, cx), tuple(coeffs), d, band, nbuf,
            open_flags=flags, gy=gy, gx=gx, gc=gc,
        )

    q, r = divmod(steps, depth)
    out = core
    if q:
        out, _ = lax.scan(
            lambda c, _: (pass_fn(c, depth), ()), out, None, length=q
        )
    if r:
        out = pass_fn(out, r)
    return out


def decompose3d(
    world: np.ndarray, topo: CartTopology, layout: TileLayout3D
) -> np.ndarray:
    """(Z, Y, X) world -> (mz, my, mx, pz, py, px) padded tiles (zero ghosts)."""
    mz, my, mx = topo.dims
    cz, cy, cx = layout.core
    if world.shape != (mz * cz, my * cy, mx * cx):
        raise ValueError(f"world {world.shape} != grid {(mz*cz, my*cy, mx*cx)}")
    tiles = np.zeros((mz, my, mx) + layout.padded_shape, dtype=world.dtype)
    hz, hy, hx = layout.halo
    for z in range(mz):
        for y in range(my):
            for x in range(mx):
                tiles[z, y, x, hz:hz + cz, hy:hy + cy, hx:hx + cx] = world[
                    z * cz:(z + 1) * cz, y * cy:(y + 1) * cy, x * cx:(x + 1) * cx
                ]
    return tiles


IMPLS3D = ("compact", "compact-pallas", "compact-strips", "compact-asm",
           "padded", "stream")  # "stream" takes an optional ":depth"

#: impl name -> compact compute backend (BASELINE.md row 9 races them)
_COMPACT_COMPUTE = {
    "compact": "xla",
    "compact-pallas": "pallas",
    "compact-strips": "pallas-strips",
    "compact-asm": "pallas-asm",
}


def make_stencil3d_program(mesh: Mesh, spec: HaloSpec3D, steps: int,
                           coeffs=JACOBI7, impl: str = "compact"):
    """The compiled 3D SPMD program (driver/bench shared): tiles ->
    tiles after ``steps`` iterations. Compact impls take/return CORE
    tiles (decompose3d_cores), 'padded' takes ghost-padded tiles
    (decompose3d)."""
    base = impl.split(":", 1)[0]
    if base not in IMPLS3D:
        raise ValueError(f"unknown 3D stencil impl {impl!r}; have {IMPLS3D}")
    if impl.startswith("compact") and len(coeffs) == 27 and impl != "compact":
        raise ValueError(
            f"27-point compact supports compute='xla' only, got {impl!r} "
            "(the banded Pallas kernels are 7-point); use impl='compact' "
            "or 'padded'"
        )
    if base == "stream":
        depth = int(impl.split(":", 1)[1]) if ":" in impl else 4
        if depth < 1:
            raise ValueError(
                f"stream depth must be >= 1, got {impl!r}"
            )
        body = lambda t: run_stencil3d_stream(  # noqa: E731
            t[0, 0, 0], spec, steps, coeffs, depth
        )[None, None, None]
    elif impl.startswith("compact"):
        compute = _COMPACT_COMPUTE[impl]
        body = lambda t: run_stencil3d_compact(  # noqa: E731
            t[0, 0, 0], spec, steps, coeffs, compute
        )[None, None, None]
    else:
        body = lambda t: run_stencil3d(  # noqa: E731
            t[0, 0, 0], spec, steps, coeffs
        )[None, None, None]
    return run_spmd(
        mesh,
        body,
        P(*mesh.axis_names, None, None, None),
        P(*mesh.axis_names, None, None, None),
    )


def decompose3d_cores(world: np.ndarray, dims: tuple[int, int, int]) -> np.ndarray:
    """(Z, Y, X) world -> (mz, my, mx, cz, cy, cx) CORE tiles (no ghosts)
    — the compact path's decomposition."""
    mz, my, mx = dims
    cz, cy, cx = (s // d for s, d in zip(world.shape, dims))
    return np.ascontiguousarray(
        world.reshape(mz, cz, my, cy, mx, cx).transpose(0, 2, 4, 1, 3, 5)
    )


def assemble3d_cores(tiles: np.ndarray) -> np.ndarray:
    """Inverse of decompose3d_cores."""
    mz, my, mx, cz, cy, cx = tiles.shape
    return tiles.transpose(0, 3, 1, 4, 2, 5).reshape(
        mz * cz, my * cy, mx * cx
    )


def assemble3d(
    tiles: np.ndarray, topo: CartTopology, layout: TileLayout3D
) -> np.ndarray:
    """Inverse of decompose3d: concatenate the cores back into the world."""
    mz, my, mx = topo.dims
    cz, cy, cx = layout.core
    hz, hy, hx = layout.halo
    world = np.zeros((mz * cz, my * cy, mx * cx), dtype=tiles.dtype)
    for z in range(mz):
        for y in range(my):
            for x in range(mx):
                world[
                    z * cz:(z + 1) * cz, y * cy:(y + 1) * cy, x * cx:(x + 1) * cx
                ] = tiles[z, y, x, hz:hz + cz, hy:hy + cy, hx:hx + cx]
    return world


def distributed_stencil3d(
    world: np.ndarray,
    steps: int,
    mesh: Optional[Mesh] = None,
    halo: tuple[int, int, int] = (1, 1, 1),
    coeffs=JACOBI7,
    periodic: bool | Sequence[bool] = True,
    impl: Optional[str] = None,
) -> np.ndarray:
    """End-to-end 3D driver: decompose over a 3-axis mesh, iterate,
    reassemble (the 3D analogue of halo.driver.distributed_stencil).

    ``impl='compact'`` carries cores and rebuilds one padded tile per
    step by concatenation — 1.6x the padded path's measured throughput
    (BASELINE.md row 9) but halo-1 only; ``impl='padded'`` carries
    ghost-padded tiles through the general exchange executor. Default
    (None) auto-selects: compact when the halo allows it.
    """
    import jax

    from tpuscratch.runtime.mesh import topology_of
    from tpuscratch.runtime.topology import factor3d

    if impl is None:
        impl = (
            "compact"
            if tuple(halo) == (1, 1, 1) and len(coeffs) in (7, 27)
            else "padded"
        )
    if impl.startswith(("compact", "stream")) and tuple(halo) != (1, 1, 1):
        raise ValueError(
            f"impl={impl!r} supports halo (1,1,1) only, got {halo}; "
            "use impl='padded' for deeper ghosts"
        )
    if mesh is None:
        mesh = make_mesh(factor3d(len(jax.devices())), ("z", "row", "col"))
    dims = tuple(mesh.devices.shape)
    topo = topology_of(mesh, periodic=periodic)
    if any(w % d for w, d in zip(world.shape, dims)):
        raise ValueError(f"world {world.shape} not divisible by mesh {dims}")
    layout = TileLayout3D(
        tuple(w // d for w, d in zip(world.shape, dims)), halo
    )
    spec = HaloSpec3D(
        layout=layout, topology=topo, axes=tuple(mesh.axis_names),
        neighbors=26 if len(coeffs) == 27 else 6,
    )
    program = make_stencil3d_program(mesh, spec, steps, coeffs, impl)
    if impl.startswith(("compact", "stream")):
        out = np.asarray(program(jnp.asarray(decompose3d_cores(world, dims))))
        return assemble3d_cores(out)
    out = program(jnp.asarray(decompose3d(world, topo, layout)))
    return assemble3d(np.asarray(out), topo, layout)
