"""The halo-exchange plan and executor.

Plan-then-execute survives from the reference (``CreateSendRecvArrays`` ->
``ExchangeData``, stencil2D.h:319-437,363-377) but both halves change
nature under XLA:

- The PLAN is built once per (layout, topology) at trace time: for each of
  the 8 directions, the send strip (core edge), the landing strip (halo
  piece on the opposite side at the receiver), the ppermute table, and a
  per-rank validity mask for open boundaries. No tags: a ppermute names
  source and destination in one table, so the reference's mirrored
  region/direction/tag bookkeeping (stencil2D.h:389-428) collapses.
- The EXECUTOR is pure dataflow: pack all 8 payloads from the pre-exchange
  tile, launch all 8 ppermutes (independent — XLA schedules/overlaps them,
  playing Waitall), then scatter the arrivals into the 8 disjoint border
  pieces. Open-boundary ranks keep their existing ghost values exactly
  where MPI_PROC_NULL would have skipped the transfer.

Corner semantics: a diagonal transfer is ONE ppermute over the tuple of
mesh axes with a flat-rank permutation table (CartTopology.send_permutation
handles periodic wrap), not a two-hop composition — one ICI hop on a torus.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Sequence

import jax.numpy as jnp
import numpy as np
from jax import lax

from tpuscratch.dtypes import SubarraySpec
from tpuscratch.runtime.topology import ALL_DIRECTIONS, CartTopology, Direction
from tpuscratch.halo.layout import TileLayout

#: 4-neighbor subset for stencils without diagonal terms (5-point).
EDGE_DIRECTIONS = (Direction.TOP, Direction.BOTTOM, Direction.LEFT, Direction.RIGHT)


@dataclasses.dataclass(frozen=True)
class Transfer:
    """One direction's worth of the plan (the reference's TransferInfo pair,
    stencil2D.h:303-311 — send and recv descriptor folded into one)."""

    direction: Direction
    send: SubarraySpec            # core strip leaving toward `direction`
    recv: SubarraySpec            # halo strip where the opposite flow lands
    perm: tuple[tuple[int, int], ...]  # flat-rank ppermute table
    has_sender: tuple[bool, ...]  # per-rank: does data arrive? (open bounds)


@dataclasses.dataclass(frozen=True)
class HaloSpec:
    """The compiled-constant description of one halo exchange."""

    layout: TileLayout
    topology: CartTopology
    axes: tuple[str, str] = ("row", "col")
    neighbors: int = 8  # 8 (corners, 9-point) or 4 (edges only, 5-point)

    def __post_init__(self):
        if self.topology.ndim != 2:
            raise ValueError("halo exchange requires a 2D topology")
        if self.neighbors not in (4, 8):
            raise ValueError("neighbors must be 4 or 8")

    def directions(self) -> tuple[Direction, ...]:
        return ALL_DIRECTIONS if self.neighbors == 8 else EDGE_DIRECTIONS

    def plan(self) -> tuple[Transfer, ...]:
        """The full transfer plan, built once per (layout, topology,
        neighbors) and cached — plans are trace-time constants.

        The native planner (native/src/halo_geometry.cpp via
        tpuscratch.native) is used when its library is built, with the
        pure-Python math as the always-available fallback; the two are
        asserted equal in tests (tests/test_native.py, tests/test_halo.py)
        so the native path is an accelerator, never a semantic fork.
        On a 64x64-rank topology the native planner cuts plan time
        ~4x (121 -> 28 ms measured; the rest is shared per-rank mask
        construction) — the reference's plan construction is likewise
        its native C++ layer (stencil2D.h:381-437)."""
        return _cached_plan(self.layout, self.topology, self.neighbors)


@functools.lru_cache(maxsize=None)
def _cached_plan(
    layout: TileLayout, topology: CartTopology, neighbors: int
) -> tuple[Transfer, ...]:
    directions = ALL_DIRECTIONS if neighbors == 8 else EDGE_DIRECTIONS
    from tpuscratch import native

    if native.available():
        raw = native.build_plan(
            topology.dims, topology.periodic,
            layout.core_h, layout.core_w, layout.halo_y, layout.halo_x,
            neighbors,
        )
        out = []
        for nat in raw:
            perm = tuple((int(a), int(b)) for a, b in nat["perm"])
            receivers = {dst for _, dst in perm}
            sy, sx, sh, sw = nat["send_rect"]
            ry, rx, rh, rw = nat["recv_rect"]
            out.append(
                Transfer(
                    direction=Direction(tuple(nat["direction"])),
                    send=SubarraySpec(offsets=(sy, sx), shape=(sh, sw)),
                    recv=SubarraySpec(offsets=(ry, rx), shape=(rh, rw)),
                    perm=perm,
                    has_sender=tuple(
                        r in receivers for r in topology.ranks()
                    ),
                )
            )
        return tuple(out)

    out = []
    for d in directions:
        # data arriving in my `d` halo was SENT toward opposite(d)
        # by my d-neighbor; build the table for that flow.
        flow = d.opposite
        perm = tuple(topology.send_permutation(flow))
        receivers = {dst for _, dst in perm}
        out.append(
            Transfer(
                direction=d,
                send=layout.send_region(flow),
                recv=layout.halo_region(d),
                perm=perm,
                has_sender=tuple(r in receivers for r in topology.ranks()),
            )
        )
    return tuple(out)


from tpuscratch.comm.collectives import _axis_index as _flat_rank  # shared row-major flat-rank helper


def halo_arrivals(tile: jnp.ndarray, spec: HaloSpec) -> list[jnp.ndarray]:
    """Phase 1: launch the transfers. Every payload packs from the
    PRE-exchange tile; the 8 ppermutes are mutually independent, so XLA is
    free to overlap them — and to overlap them with any compute that does
    not consume the arrivals (see stencil.stencil_step's 'overlap' impl)."""
    if tuple(tile.shape) != spec.layout.padded_shape:
        raise ValueError(
            f"tile {tile.shape} != padded {spec.layout.padded_shape} "
            "(batched tiles are not supported; vmap over the exchange instead)"
        )
    return [
        lax.ppermute(t.send.region(tile), spec.axes, list(t.perm))
        for t in spec.plan()
    ]


def halo_scatter(
    tile: jnp.ndarray, spec: HaloSpec, arrivals: list[jnp.ndarray]
) -> jnp.ndarray:
    """Phase 2: land the arrivals in the (disjoint) border pieces.

    Open boundary = no sender: keep the existing ghost values
    (MPI_PROC_NULL semantics), selected by a static per-rank table indexed
    with the runtime rank.
    """
    plan = spec.plan()
    me = _flat_rank(tuple(spec.axes))
    out = tile
    for t, arrived in zip(plan, arrivals):
        if all(t.has_sender):
            update = arrived
        else:
            mask = jnp.asarray(np.array(t.has_sender))[me]
            update = jnp.where(mask, arrived, t.recv.region(out))
        out = lax.dynamic_update_slice(out, update, t.recv.offsets)
    return out


def halo_exchange(tile: jnp.ndarray, spec: HaloSpec) -> jnp.ndarray:
    """Fill ``tile``'s ghost border from its 8 (or 4) mesh neighbors.

    SPMD: call inside shard_map over ``spec.axes``; ``tile`` is the local
    padded tile. Returns the tile with refreshed halo; the core is
    untouched. The reference's hot loop (ExchangeData, stencil2D.h:363-377).
    """
    return halo_scatter(tile, spec, halo_arrivals(tile, spec))
