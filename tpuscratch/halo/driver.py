"""Whole-grid drivers: decompose, iterate, reassemble.

The user-facing layer the reference implements in its driver mains
(/root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:35-100): build the
process grid, cut the world into per-rank tiles with ghost borders, loop
exchange+compute, dump results. Here the decomposition is pure reshaping,
the loop is one compiled shard_map program, and the "dump" is just the
reassembled array.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.halo.exchange import HaloSpec
from tpuscratch.halo.layout import TileLayout
from tpuscratch.halo.stencil import (
    run_stencil,
    run_stencil_deep,
    run_stencil_resident,
)
from tpuscratch.runtime.mesh import make_mesh_2d, topology_of
from tpuscratch.runtime.topology import CartTopology


def decompose(world: np.ndarray, topo: CartTopology, layout: TileLayout) -> np.ndarray:
    """Cut a (rows*core_h, cols*core_w) world into padded per-rank tiles of
    shape (rows, cols, padded_h, padded_w); ghost borders start at zero
    (they are filled by the first exchange)."""
    rows, cols = topo.dims
    th, tw = layout.core_h, layout.core_w
    if world.shape != (rows * th, cols * tw):
        raise ValueError(
            f"world {world.shape} != grid {(rows * th, cols * tw)}"
        )
    tiles = np.zeros((rows, cols) + layout.padded_shape, dtype=world.dtype)
    hy, hx = layout.halo_y, layout.halo_x
    for r in range(rows):
        for c in range(cols):
            tiles[r, c, hy : hy + th, hx : hx + tw] = world[
                r * th : (r + 1) * th, c * tw : (c + 1) * tw
            ]
    return tiles


def assemble(tiles: np.ndarray, topo: CartTopology, layout: TileLayout) -> np.ndarray:
    """Inverse of decompose: concatenate the cores back into the world."""
    rows, cols = topo.dims
    th, tw = layout.core_h, layout.core_w
    hy, hx = layout.halo_y, layout.halo_x
    world = np.zeros((rows * th, cols * tw), dtype=tiles.dtype)
    for r in range(rows):
        for c in range(cols):
            world[r * th : (r + 1) * th, c * tw : (c + 1) * tw] = tiles[
                r, c, hy : hy + th, hx : hx + tw
            ]
    return world


def make_stencil_program(
    mesh: Mesh,
    spec: HaloSpec,
    steps: int,
    coeffs=(0.25, 0.25, 0.25, 0.25, 0.0),
    impl: str = "xla",
    unroll: int | None = None,
):
    """The compiled SPMD program: (rows, cols, ph, pw) tiles -> same, after
    ``steps`` exchange+compute iterations. ``impl='deep'`` selects the
    communication-avoiding trapezoid scheme (depth = the layout halo
    width); ``impl='resident'`` the single-device VMEM-resident kernel;
    ``impl='dma'`` the double-buffered remote-DMA Pallas kernel
    (ops.halo_dma — core VMEM-resident, halo strips by async DMA; takes
    9-point coeffs too, corners riding the DMA); ``impl='dma-deep:k'``
    the same kernel folding k substeps per exchange in-kernel;
    ``impl='dma-hbm'`` the HBM-resident banded variant for cores beyond
    VMEM (the core streams through in row bands, strips still on the
    DMA engine — serves the 8192^2-class tiles ``dma`` must refuse).
    ``unroll`` is the scan unroll factor for the per-step impls and the
    kernel's inner unroll for 'resident' (defaults 1 and 8)."""
    if len(coeffs) == 9 and impl != "xla" and not impl.startswith(
        ("dma", "stream")
    ):
        raise ValueError(
            f"9-point coeffs need impl='xla', a dma impl, or 'stream:k', "
            f"got {impl!r}"
        )
    if impl == "resident":
        step_fn = lambda t: run_stencil_resident(t[0, 0], spec, steps, coeffs, unroll=8 if unroll is None else unroll)[None, None]  # noqa: E731
    elif impl == "dma-hbm":
        from tpuscratch.ops.halo_dma import run_stencil_dma_hbm

        step_fn = lambda t: run_stencil_dma_hbm(t[0, 0], spec, steps, coeffs)[None, None]  # noqa: E731
    elif impl == "stream" or impl.startswith("stream:"):
        from tpuscratch.halo.stencil import run_stencil_stream

        sdepth = int(impl.split(":", 1)[1]) if ":" in impl else 8
        if sdepth < 1:
            raise ValueError(f"stream depth must be >= 1, got {impl!r}")
        step_fn = lambda t: run_stencil_stream(t[0, 0], spec, steps, coeffs, sdepth)[None, None]  # noqa: E731
    elif impl == "dma" or impl.startswith("dma-deep:"):
        from tpuscratch.ops.halo_dma import run_stencil_dma

        depth = int(impl.split(":", 1)[1]) if ":" in impl else 1
        step_fn = lambda t: run_stencil_dma(t[0, 0], spec, steps, coeffs, depth)[None, None]  # noqa: E731
    elif impl in ("deep", "deep-pallas"):
        sub = "pallas" if impl == "deep-pallas" else "xla"
        step_fn = lambda t: run_stencil_deep(t[0, 0], spec, steps, coeffs, impl=sub)[None, None]  # noqa: E731
    else:
        step_fn = lambda t: run_stencil(t[0, 0], spec, steps, coeffs, impl, unroll or 1)[None, None]  # noqa: E731
    return run_spmd(
        mesh,
        step_fn,
        P(*mesh.axis_names, None, None),
        P(*mesh.axis_names, None, None),
    )


def _setup(world_shape, mesh: Optional[Mesh], halo, periodic: bool,
           neighbors: int = 8):
    """Shared driver prologue: default mesh, topology, divisibility check,
    layout and spec construction."""
    mesh = mesh if mesh is not None else make_mesh_2d()
    topo = topology_of(mesh, periodic=periodic)
    rows, cols = topo.dims
    if world_shape[0] % rows or world_shape[1] % cols:
        raise ValueError(f"world {world_shape} not divisible by mesh {topo.dims}")
    layout = TileLayout(
        world_shape[0] // rows, world_shape[1] // cols, halo[0], halo[1]
    )
    spec = HaloSpec(layout=layout, topology=topo, axes=tuple(mesh.axis_names),
                    neighbors=neighbors)
    return mesh, topo, layout, spec


def checkpointed_stencil(
    world: np.ndarray,
    steps: int,
    ckpt_dir: str,
    save_every: int = 100,
    mesh: Optional[Mesh] = None,
    halo: tuple[int, int] = (1, 1),
    coeffs=(0.25, 0.25, 0.25, 0.25, 0.0),
    impl: str = "xla",
    periodic: bool = True,
    keep: int = 3,
    sink=None,
    chaos=None,
    recorder=None,
    reshard: bool = False,
    async_ckpt: bool = False,
) -> np.ndarray:
    """``distributed_stencil`` with preemption survival: the tile state is
    checkpointed every ``save_every`` steps and the run RESUMES from the
    newest checkpoint in ``ckpt_dir`` when one exists.

    ``reshard=True`` makes the resume ELASTIC over the mesh shape: a
    checkpoint whose tiles were decomposed for a different process grid
    (a preempted-and-shrunk slice) is loaded in its saved layout,
    reassembled to the world, and re-decomposed onto THIS mesh — the
    cores round-trip exactly (ghosts are refilled by every step's
    leading exchange), so the continued run computes the same cells.
    Off (the default), a mismatched-mesh resume fails loudly at leaf
    validation.

    ``async_ckpt=True`` switches the saves to the snapshot-then-publish
    path (``runtime.async_ckpt``): the loop pays only the host-copy
    wall (``ckpt/snapshot`` events), the serialize+publish overlaps the
    next chunk on a background writer (``ckpt/write``), and the barrier
    drains before each snapshot, at preemption points, and at exit —
    published checkpoints byte-identical to the blocking path's.

    ``sink`` (an ``obs.sink.Sink``) receives one ``halo/chunk`` event
    per save chunk — step reached, fenced wall seconds, cell-updates/s —
    plus one ``ckpt/save`` event per save (its wall seconds feed the
    goodput checkpoint bucket) — the same telemetry the trainer emits.
    ``recorder`` (an ``obs.trace.FlightRecorder``; a fresh bounded one
    when absent) collects ``halo/chunk``/``ckpt/save`` spans for
    Chrome-trace export and emits cumulative ``trace/phase`` totals at
    the end of the run.

    ``chaos`` (an ``ft.ChaosPlan``) plugs the fault injector in: a
    transient ``comm/halo_chunk`` CommError around each compiled chunk,
    checkpoint-IO faults through ``save``'s stage hook (saves run under
    ``ft.retry``), and ``halo/preempt`` — a simulated preemption AFTER a
    chunk's save, the supervisor's restartable signal.  Absent (the
    default), no hook code runs.

    The reference runs under scheduler walltime kills with no way to
    continue (per-rank result dumps only, mpi-2d-stencil-subarray.cpp:62;
    SURVEY.md §5 records the gap); here a killed run re-invoked with the
    same arguments continues where the last atomic save landed and
    produces a BIT-IDENTICAL result to an uninterrupted run (same chunk
    boundaries, exact f32 round trip through the .npy format —
    tests/test_checkpoint_resume.py kills a run mid-flight to prove it).
    """
    return checkpointed_stencil_program(
        world, steps, ckpt_dir, save_every=save_every, mesh=mesh, halo=halo,
        coeffs=coeffs, impl=impl, periodic=periodic, keep=keep, sink=sink,
        chaos=chaos, recorder=recorder, reshard=reshard,
        async_ckpt=async_ckpt,
    ).run()


def checkpointed_stencil_program(
    world: np.ndarray,
    steps: int,
    ckpt_dir: str,
    save_every: int = 100,
    mesh: Optional[Mesh] = None,
    halo: tuple[int, int] = (1, 1),
    coeffs=(0.25, 0.25, 0.25, 0.25, 0.0),
    impl: str = "xla",
    periodic: bool = True,
    keep: int = 3,
    sink=None,
    chaos=None,
    recorder=None,
    reshard: bool = False,
    async_ckpt: bool = False,
    workload: str = "halo",
):
    """:func:`checkpointed_stencil` as a steppable
    ``runtime.chunked.ChunkedProgram`` — same arguments, same event
    stream, same bit-identical resume contract, but the chunk loop is
    the shared runtime's, so a ``MeshScheduler`` can time-slice the
    stencil against other workloads at save boundaries.  ``run()``
    returns the assembled world; ``workload`` tags every emitted
    event."""
    from tpuscratch.runtime import checkpoint
    from tpuscratch.obs.sink import NullSink
    from tpuscratch.obs.trace import FlightRecorder, emit_phase_totals
    from tpuscratch.runtime.chunked import (
        ChunkedProgram,
        ChunkResult,
        WorkloadSink,
    )

    if save_every < 1:
        raise ValueError(f"save_every must be >= 1, got {save_every}")
    sink = WorkloadSink(sink if sink is not None else NullSink(), workload)
    rec = recorder if recorder is not None else FlightRecorder()
    mesh, topo, layout, spec = _setup(world.shape, mesh, halo, periodic)

    tiles = decompose(world, topo, layout)
    start = 0
    if checkpoint.latest_step(ckpt_dir) is not None:
        tiles, start, _meta = checkpoint.restore(ckpt_dir, tiles,
                                                 reshard=reshard)
        if start > steps:
            raise ValueError(
                f"checkpoint in {ckpt_dir} is at step {start}, beyond the "
                f"requested {steps} — refusing to return an over-stepped "
                "state as the answer (use a fresh ckpt_dir)"
            )
        if tiles.shape[:2] != tuple(topo.dims):
            # elastic resume: the saved decomposition was for another
            # process grid — reassemble the world from the old layout
            # (cores only; ghosts are refilled by the leading exchange
            # of every step) and re-cut it for THIS mesh
            r0, c0 = tiles.shape[:2]
            old_layout = TileLayout(world.shape[0] // r0,
                                    world.shape[1] // c0,
                                    layout.halo_y, layout.halo_x)
            old_topo = CartTopology((r0, c0), (periodic, periodic))
            tiles = decompose(assemble(tiles, old_topo, old_layout),
                              topo, layout)
    sink.emit(
        "halo/config",
        world_h=world.shape[0], world_w=world.shape[1], steps=steps,
        impl=impl, mesh=f"{topo.dims[0]}x{topo.dims[1]}",
        resumed_at=start,
    )
    cells = world.shape[0] * world.shape[1]
    save_policy = None
    if chaos is not None:
        from tpuscratch.ft.retry import DEFAULT_SAVE_RETRY

        save_policy = DEFAULT_SAVE_RETRY
    hal = {"state": jnp.asarray(tiles),
           "programs": {}}  # chunk size -> compiled program

    def remake():
        return checkpointed_stencil_program(
            world, steps, ckpt_dir, save_every=save_every, mesh=mesh,
            halo=halo, coeffs=coeffs, impl=impl, periodic=periodic,
            keep=keep, sink=sink, chaos=chaos, recorder=recorder,
            reshard=reshard, async_ckpt=async_ckpt, workload=workload,
        )

    def run_chunk(cp, pos):
        chunk = min(save_every, steps - pos)
        fresh = chunk not in hal["programs"]
        if fresh:
            # a freshly-built program jit-compiles inside this chunk's
            # first call, so the bracket is compile-dominated wall — the
            # trainer's CompileCounter convention at chunk granularity;
            # obs.goodput carves compile_s out of the step bucket
            hal["programs"][chunk] = make_stencil_program(
                mesh, spec, chunk, coeffs, impl
            )
        hal["state"] = jax.block_until_ready(hal["programs"][chunk](hal["state"]))
        return chunk, fresh

    def make_event(cp, pos, payload, chunk_sp):
        chunk, fresh = payload
        chunk_s = chunk_sp.seconds
        return ChunkResult(pos=pos + chunk, event={
            "step": pos + chunk, "chunk": chunk, "wall_s": round(chunk_s, 6),
            "cell_updates_per_s": round(cells * chunk / chunk_s, 3),
            "compile_s": round(chunk_s, 6) if fresh else 0.0,
        })

    def snapshot(cp, pos):
        return np.asarray(hal["state"]), {"steps_total": steps, "impl": impl}

    def epilogue(cp):
        emit_phase_totals(cp.sink, cp.rec)
        cp.sink.flush()
        return assemble(np.asarray(hal["state"]), topo, layout)

    return ChunkedProgram(
        workload=workload, prefix="halo", total=steps, pos=start,
        run_chunk=run_chunk, make_event=make_event, snapshot=snapshot,
        epilogue=epilogue, fail_site="comm/halo_chunk", fail_op="halo_chunk",
        preempt_site="halo/preempt", ckpt_dir=ckpt_dir, keep=keep,
        save_retry=save_policy, async_ckpt=async_ckpt, sink=sink,
        recorder=rec, chaos=chaos, remake=remake,
    )


def distributed_stencil(
    world: np.ndarray,
    steps: int,
    mesh: Optional[Mesh] = None,
    halo: tuple[int, int] = (1, 1),
    coeffs=(0.25, 0.25, 0.25, 0.25, 0.0),
    impl: str = "xla",
    periodic: bool = True,
    sink=None,
) -> np.ndarray:
    """End-to-end convenience: decompose over the mesh (default: all
    devices, most-square), iterate, reassemble. A 1x1 mesh gives the
    single-device periodic stencil (the self-wrap halo exchange).
    ``sink`` receives one ``halo/run`` event (fenced wall seconds,
    cell-updates/s — compile included: this entry point runs once)."""
    mesh, topo, layout, spec = _setup(world.shape, mesh, halo, periodic)
    program = make_stencil_program(mesh, spec, steps, coeffs, impl)
    t0 = time.perf_counter()
    out = jax.block_until_ready(program(jnp.asarray(decompose(world, topo, layout))))
    if sink is not None:
        wall = time.perf_counter() - t0
        sink.emit(
            "halo/run",
            world_h=world.shape[0], world_w=world.shape[1], steps=steps,
            impl=impl, mesh=f"{topo.dims[0]}x{topo.dims[1]}",
            wall_s=round(wall, 6),
            cell_updates_per_s=round(
                world.shape[0] * world.shape[1] * steps / wall, 3
            ),
        )
        sink.flush()
    return assemble(np.asarray(out), topo, layout)
