"""Stencil compute + the exchange-compute iteration loop.

The reference driver's loop body is ``do {Exchange; Compute} while
(!TerminateCondition)`` with a **no-op** Compute and a single iteration
(/root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:27-31,92-95). Here
Compute is a real 5-point update (so benchmarks measure something), the
loop is a ``lax.scan`` (one compiled program for N steps, no per-step
dispatch), and the whole iteration is differentiable/jittable like any jax
code. A Pallas fused kernel variant lives in ops/stencil_kernel.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpuscratch.halo.exchange import (
    HaloSpec,
    halo_arrivals,
    halo_exchange,
    halo_scatter,
)
from tpuscratch.halo.layout import TileLayout


def five_point(tile: jax.Array, layout: TileLayout, coeffs=(0.25, 0.25, 0.25, 0.25, 0.0)) -> jax.Array:
    """One Jacobi-style 5-point update of the core; halo is read, not
    written. ``coeffs`` = (north, south, west, east, center).

    Defaults to the Laplace/Jacobi average — the canonical workload for a
    halo benchmark.
    """
    if layout.halo_y < 1 or layout.halo_x < 1:
        raise ValueError(
            f"five_point needs halo >= 1, got ({layout.halo_y},{layout.halo_x})"
        )
    new_core = _new_values(
        tile, 0, layout.core_h, 0, layout.core_w, layout, coeffs
    )
    return rebuild(tile, new_core, layout)


def nine_point(
    tile: jax.Array,
    layout: TileLayout,
    coeffs=(0.125, 0.125, 0.125, 0.125, 0.0625, 0.0625, 0.0625, 0.0625, 0.0),
) -> jax.Array:
    """One 9-point update of the core — the stencil shape that actually
    READS the corner ghosts the 8-neighbor exchange fills (a 5-point
    stencil leaves the diagonal transfers write-only). ``coeffs`` =
    (north, south, west, east, nw, ne, sw, se, center); the default is
    the 2D Mehrstellen/blur-style weighting.
    """
    if layout.halo_y < 1 or layout.halo_x < 1:
        raise ValueError(
            f"nine_point needs halo >= 1, got ({layout.halo_y},{layout.halo_x})"
        )
    hy, hx = layout.halo_y, layout.halo_x
    h, w = layout.core_h, layout.core_w
    sl = lambda dy, dx: tile[  # noqa: E731
        hy + dy : hy + dy + h, hx + dx : hx + dx + w
    ]
    cn, cs, cw, ce, cnw, cne, csw, cse, cc = coeffs
    new_core = (
        cn * sl(-1, 0) + cs * sl(1, 0) + cw * sl(0, -1) + ce * sl(0, 1)
        + cnw * sl(-1, -1) + cne * sl(-1, 1)
        + csw * sl(1, -1) + cse * sl(1, 1)
        + cc * sl(0, 0)
    )
    return rebuild(tile, new_core, layout)


def rebuild(tile: jax.Array, new_core: jax.Array, layout: TileLayout) -> jax.Array:
    """Wrap a freshly-computed core back into the padded tile's border.

    By concatenation, NOT dynamic_update_slice: an in-place core update
    fused with overlapping shifted reads of the same buffer miscompiles on
    XLA:CPU under shard_map (Gauss-Seidel-like partial reads; even
    optimization_barrier does not prevent it — found by the steps=1 oracle
    test). Concat allocates a fresh buffer by construction and fuses just
    as well.
    """
    hy, hx = layout.halo_y, layout.halo_x
    h, w = layout.core_h, layout.core_w
    mid = jnp.concatenate(
        [tile[hy : hy + h, :hx], new_core, tile[hy : hy + h, hx + w :]], axis=1
    )
    return jnp.concatenate([tile[:hy], mid, tile[hy + h :]], axis=0)


def _compute(tile: jax.Array, layout: TileLayout, coeffs, impl: str) -> jax.Array:
    if len(coeffs) == 9:
        if impl != "xla":
            raise ValueError(
                f"9-point coeffs are only supported by impl='xla', got {impl!r}"
            )
        return nine_point(tile, layout, coeffs)
    if impl == "xla":
        return five_point(tile, layout, coeffs)
    if impl == "pallas":
        from tpuscratch.ops.stencil_kernel import five_point_pallas

        return five_point_pallas(tile, layout, tuple(coeffs))
    if impl == "blocked":
        from tpuscratch.ops.stencil_kernel import five_point_blocked

        return five_point_blocked(tile, layout, tuple(coeffs))
    raise ValueError(f"unknown stencil impl {impl!r}")


def _new_values(t: jax.Array, r0: int, r1: int, c0: int, c1: int, layout, coeffs) -> jax.Array:
    """Updated values for core cells rows [r0,r1) x cols [c0,c1), read from
    the (padded-coordinate) tile ``t``."""
    hy, hx = layout.halo_y, layout.halo_x
    cn, cs, cw, ce, cc = coeffs
    ry, rx = hy + r0, hx + c0
    h, w = r1 - r0, c1 - c0
    return (
        cn * t[ry - 1 : ry - 1 + h, rx : rx + w]
        + cs * t[ry + 1 : ry + 1 + h, rx : rx + w]
        + cw * t[ry : ry + h, rx - 1 : rx - 1 + w]
        + ce * t[ry : ry + h, rx + 1 : rx + 1 + w]
        + cc * t[ry : ry + h, rx : rx + w]
    )


def stencil_step_overlap(tile: jax.Array, spec: HaloSpec, coeffs=(0.25, 0.25, 0.25, 0.25, 0.0)) -> jax.Array:
    """Exchange overlapped with interior compute — the async-halo variant.

    The interior of the core (every cell at least one stencil reach away
    from the core edge) reads only core cells, so its update is computed
    from the PRE-exchange tile with no data dependency on the transfers:
    XLA is free to run the 8 ppermutes concurrently with the bulk of the
    FLOPs. Only the 1-cell boundary ring of the core waits for the
    arrivals. The reference analogue is the Isend-all/compute/Waitall
    overlap pattern its plan-executor design enables (SURVEY.md §7.5).
    """
    lay = spec.layout
    if len(coeffs) != 5:
        raise ValueError("the overlap impl supports 5-point coeffs only")
    if lay.halo_y < 1 or lay.halo_x < 1:
        raise ValueError("five_point needs halo >= 1 on both axes")
    h, w = lay.core_h, lay.core_w
    if h < 3 or w < 3:
        # no interior to overlap; fall back to the plain step
        return five_point(halo_exchange(tile, spec), lay, coeffs)

    arrivals = halo_arrivals(tile, spec)                  # transfers launch
    interior = _new_values(tile, 1, h - 1, 1, w - 1, lay, coeffs)  # overlaps
    t2 = halo_scatter(tile, spec, arrivals)               # halo lands

    top = _new_values(t2, 0, 1, 0, w, lay, coeffs)
    bottom = _new_values(t2, h - 1, h, 0, w, lay, coeffs)
    left = _new_values(t2, 1, h - 1, 0, 1, lay, coeffs)
    right = _new_values(t2, 1, h - 1, w - 1, w, lay, coeffs)

    mid = jnp.concatenate([left, interior, right], axis=1)
    new_core = jnp.concatenate([top, mid, bottom], axis=0)
    return rebuild(t2, new_core, lay)


def stencil_step(tile: jax.Array, spec: HaloSpec, coeffs=(0.25, 0.25, 0.25, 0.25, 0.0), impl: str = "xla") -> jax.Array:
    """Exchange then compute — one iteration of the flagship loop.

    ``impl`` selects the compute path — the runtime analogue of the
    reference's compile-time GPU/CPU switch: 'xla' (compiler-fused),
    'pallas' (whole-tile VMEM kernel, ops/stencil_kernel.py), 'blocked'
    (row-band VMEM kernel for cores too large for one block,
    ``five_point_blocked``), or 'overlap' (interior compute overlapped
    with the halo transfers, ``stencil_step_overlap``).
    """
    if impl not in ("xla", "pallas", "blocked", "overlap"):
        raise ValueError(f"unknown stencil impl {impl!r}")
    if len(coeffs) == 9 and spec.neighbors != 8:
        raise ValueError(
            "9-point coeffs need a neighbors=8 HaloSpec: a 4-neighbor "
            "exchange never fills the corner ghosts the stencil reads"
        )
    if impl == "overlap":
        return stencil_step_overlap(tile, spec, coeffs)
    tile = halo_exchange(tile, spec)
    return _compute(tile, spec.layout, coeffs, impl)


def run_stencil(tile: jax.Array, spec: HaloSpec, steps: int, coeffs=(0.25, 0.25, 0.25, 0.25, 0.0), impl: str = "xla", unroll: int = 1) -> jax.Array:
    """N iterations as one compiled scan (SPMD: call inside shard_map)."""

    def body(t, _):
        return stencil_step(t, spec, coeffs, impl), ()

    out, _ = lax.scan(body, tile, None, length=steps, unroll=unroll)
    return out


def run_stencil_resident(tile: jax.Array, spec: HaloSpec, steps: int, coeffs=(0.25, 0.25, 0.25, 0.25, 0.0), unroll: int = 8) -> jax.Array:
    """N iterations entirely in VMEM — the single-device fast path.

    On a 1x1 periodic topology the halo exchange is a self-wrap: every
    ghost strip comes from the tile's own opposite edge. That makes the
    ghost cells redundant — periodic wrap is just modular indexing of the
    core — so the whole loop collapses into one VMEM-resident Pallas
    kernel (ops.stencil_kernel.resident_periodic_pallas) with zero HBM
    traffic between steps. Returns a padded tile with the halo re-wrapped
    (one trailing exchange), so the result is interchangeable with
    ``run_stencil``'s.
    """
    lay = spec.layout
    if spec.topology.dims != (1, 1):
        raise ValueError(
            f"resident stencil is single-device only, got mesh {spec.topology.dims}"
        )
    if not all(spec.topology.periodic):
        # design decision: the kernel's whole economy is modular
        # indexing of the core (wrap == free); zero-ghost open edges
        # would reintroduce the border bookkeeping it exists to shed.
        # Open boundaries run on run_stencil or run_stencil_deep
        # impl='xla' (open-aware trapezoid).
        raise ValueError(
            "resident stencil requires a periodic topology; use "
            "run_stencil or run_stencil_deep(impl='xla') for open "
            "boundaries"
        )
    from tpuscratch.ops.stencil_kernel import resident_periodic_pallas

    hy, hx = lay.halo_y, lay.halo_x
    core = tile[hy : hy + lay.core_h, hx : hx + lay.core_w]
    new_core = resident_periodic_pallas(core, steps, tuple(coeffs), unroll)
    return halo_exchange(rebuild(tile, new_core, lay), spec)


def run_stencil_stream(
    tile: jax.Array,
    spec: HaloSpec,
    steps: int,
    coeffs=(0.25, 0.25, 0.25, 0.25, 0.0),
    depth: int = 8,
    band: int | None = None,
) -> jax.Array:
    """``steps`` iterations via the row-banded streamed kernel
    (ops/stencil_stream.nine_point_streamed_2d): ``depth`` substeps fold
    into each manual-DMA pass, dividing per-step HBM traffic by
    ``depth`` — the 2D form of the deep-z streamed kernel, for grids
    beyond VMEM (where ``resident`` refuses).  Serves ANY cartesian
    layout (the reference's exchange generality, stencil2D.h:232-244,
    mpi10.cpp:27): a periodic column axis of size 1 self-wraps in-kernel
    (wrap mode, zero ghost machinery); distributed or open columns ride
    ghost-column slabs — x-neighbor edge columns with the diagonal
    neighbors' corner blocks, the 8-channel transfer set at ghost depth
    ``depth`` — patched into each band's window (ghost mode).  Row
    ghosts travel as (depth, W) slabs either way; ONE exchange per
    ``depth`` steps.  5-point AND 9-point coefficients.  Open ends
    re-impose zero ghosts per substep via per-rank traced flags.
    Takes/returns a padded tile (trailing exchange), interchangeable
    with the other impls.
    """
    from tpuscratch.ops.stencil_stream import nine_point_streamed_2d

    lay = spec.layout
    topo = spec.topology
    if tuple(tile.shape) != lay.padded_shape:
        raise ValueError(f"tile {tile.shape} != padded {lay.padded_shape}")
    H, W = lay.core_h, lay.core_w
    hy, hx = lay.halo_y, lay.halo_x
    core = tile[hy : hy + H, hx : hx + W]
    wrap_x = topo.dims[1] == 1 and topo.periodic[1]

    def gather(block, off):
        # the off-neighbor's block: local when the permutation is pure
        # self-wrap (self-ppermutes cost real launch time on chip,
        # BASELINE row 9), zeros when nobody sends (fully open), else a
        # (diagonal-capable) ppermute — open-edge ranks are zero-filled
        # by ppermute semantics, the MPI_PROC_NULL analogue
        pairs = list(topo.send_permutation(off))
        if not pairs:
            return jnp.zeros_like(block)
        if len(pairs) == topo.size and all(s == d for s, d in pairs):
            return block
        return lax.ppermute(block, spec.axes, pairs)

    def open_flags():
        # [top, bottom, left, right]; None when fully periodic
        if all(topo.periodic):
            return None
        parts = []
        for axis in (0, 1):
            if topo.periodic[axis]:
                parts += [jnp.zeros((), jnp.int32)] * 2
            elif topo.dims[axis] == 1:
                parts += [jnp.ones((), jnp.int32)] * 2
            else:
                rc = lax.axis_index(spec.axes[axis])
                parts += [(rc == 0).astype(jnp.int32),
                          (rc == topo.dims[axis] - 1).astype(jnp.int32)]
        return jnp.stack(parts)

    flags = open_flags()

    def pass_fn(c, d):
        a_top = gather(c[H - d :], (1, 0))
        a_bot = gather(c[:d], (-1, 0))
        if wrap_x:
            gl = gr = None
        else:
            # (H + 2d, d) column slabs spanning global rows [-d, H + d):
            # [diag corner | x-neighbor edge columns | diag corner]
            gl = jnp.concatenate(
                [gather(c[H - d :, W - d :], (1, 1)),
                 gather(c[:, W - d :], (0, 1)),
                 gather(c[:d, W - d :], (-1, 1))], axis=0
            )
            gr = jnp.concatenate(
                [gather(c[H - d :, :d], (1, -1)),
                 gather(c[:, :d], (0, -1)),
                 gather(c[:d, :d], (-1, -1))], axis=0
            )
        return nine_point_streamed_2d(
            c, a_top, a_bot, (H, W), tuple(coeffs), d, band,
            open_flags=flags, gl=gl, gr=gr,
        )

    q, r = divmod(steps, depth)
    out = core
    if q:
        out, _ = lax.scan(
            lambda c, _: (pass_fn(c, depth), ()), out, None, length=q
        )
    if r:
        out = pass_fn(out, r)
    return halo_exchange(rebuild(tile, out, lay), spec)


def shrink_step(a: jax.Array, coeffs) -> jax.Array:
    """One valid-region Jacobi step: (H, W) -> (H-2, W-2), every output
    cell computed from fully-valid neighbors. The building block of the
    trapezoid scheme — no border bookkeeping, the shape IS the validity."""
    H, W = a.shape
    h, w = H - 2, W - 2
    cn, cs, cw, ce, cc = coeffs
    return (
        cn * a[0:h, 1 : 1 + w]
        + cs * a[2 : 2 + h, 1 : 1 + w]
        + cw * a[1 : 1 + h, 0:w]
        + ce * a[1 : 1 + h, 2 : 2 + w]
        + cc * a[1 : 1 + h, 1 : 1 + w]
    )


def run_stencil_deep(tile: jax.Array, spec: HaloSpec, steps: int, coeffs=(0.25, 0.25, 0.25, 0.25, 0.0), depth: int | None = None, impl: str = "xla") -> jax.Array:
    """Communication-avoiding iteration: one ``depth``-wide halo exchange
    buys ``depth`` update substeps (trapezoid/ghost-zone scheme).

    Each exchange fills a halo ``depth`` cells deep; substep j then updates
    every cell at least j rings in from the padded border, so after
    ``depth`` substeps the core has advanced ``depth`` true Jacobi steps —
    the redundant ring computation is the price for ``depth``x fewer
    exchanges (and a ``depth``x shorter scan). The distributed win is
    fewer, larger ICI messages; single-chip, it drops the per-step
    pack/permute/scatter entirely. This is the natural TPU extension of
    the reference's ghost-cell machinery, whose halo width is already
    ``stencil/2`` cells (stencil2D.h:116-117) — here the width is an
    optimization knob rather than a stencil property.

    Open (non-periodic) boundaries are supported on the ``xla`` impl:
    a physical edge's ghost rings must stay ZERO at every substep (the
    MPI_PROC_NULL semantics of the reference, mpi5.cpp:47-75 1D ends,
    mpi10.cpp:27 non-periodic cart grid), so after each substep the
    rings still acting as ghosts on an open side are re-zeroed — via
    per-rank traced flags, since shard_map traces one program for every
    rank. The ``pallas`` trapezoid kernel remains periodic-only (use
    ``impl='xla'`` deep, or the plain per-step paths, on open
    topologies). ``depth`` defaults to the layout halo width; steps
    need not divide evenly (the remainder runs as a shallower trailing
    trapezoid).

    ``impl='xla'`` runs the substep pyramid as compiler-scheduled ops
    (about one HBM pass per substep); ``impl='pallas'`` runs the whole
    pyramid inside one VMEM-resident kernel (one HBM read + one write per
    ``depth`` substeps — ops/stencil_kernel.deep_trapezoid_pallas), the
    memory-bound regime's win.
    """
    lay = spec.layout
    k = lay.halo_y if depth is None else depth
    if lay.halo_y != lay.halo_x:
        raise ValueError("deep stencil needs a square halo (halo_y == halo_x)")
    if not (1 <= k <= lay.halo_y):
        raise ValueError(f"depth {k} must be in [1, halo {lay.halo_y}]")
    topo = spec.topology
    open_any = not all(topo.periodic)
    if open_any and impl == "pallas":
        raise ValueError(
            "the pallas trapezoid kernel is periodic-only; use "
            "impl='xla' deep (open-boundary aware) or a per-step impl"
        )
    if min(lay.core_h, lay.core_w) < k:
        raise ValueError(
            f"core {lay.core_h}x{lay.core_w} smaller than depth {k}"
        )
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown deep stencil impl {impl!r}")

    def open_side_flags():
        # 1.0 marks a side whose ghosts are a physical open edge for
        # THIS rank (traced: one program serves every rank)
        flags = []
        for axis in (0, 1):
            if topo.periodic[axis]:
                flags += [0.0, 0.0]
            elif topo.dims[axis] == 1:
                flags += [1.0, 1.0]
            else:
                c = lax.axis_index(spec.axes[axis])
                flags += [(c == 0).astype(tile.dtype),
                          (c == topo.dims[axis] - 1).astype(tile.dtype)]
        return [jnp.asarray(f, tile.dtype) for f in flags]

    flags = open_side_flags() if open_any else None

    def zero_open_margins(a, g):
        # the g outermost rings still acting as ghosts must stay zero
        # on open sides (they are real evolving data on periodic or
        # interior sides)
        f_my, f_py, f_mx, f_px = flags
        a = a.at[:g, :].multiply(1 - f_my)
        a = a.at[-g:, :].multiply(1 - f_py)
        a = a.at[:, :g].multiply(1 - f_mx)
        a = a.at[:, -g:].multiply(1 - f_px)
        return a

    def trapezoid(t, substeps):
        t = halo_exchange(t, spec)
        if impl == "pallas":
            from tpuscratch.ops.stencil_kernel import deep_trapezoid_pallas

            core = deep_trapezoid_pallas(t, lay, substeps, tuple(coeffs))
        else:
            a = t
            for j in range(1, substeps + 1):
                a = shrink_step(a, coeffs)
                g = lay.halo_y - j
                if open_any and g > 0 and j < substeps:
                    a = zero_open_margins(a, g)
            crop = lay.halo_y - substeps
            core = a[crop:-crop, crop:-crop] if crop else a
        return rebuild(t, core, lay)

    rounds, rem = divmod(steps, k)

    def body(t, _):
        return trapezoid(t, k), ()

    out, _ = lax.scan(body, tile, None, length=rounds)
    if rem:
        out = trapezoid(out, rem)
    return out
