"""Stencil compute + the exchange-compute iteration loop.

The reference driver's loop body is ``do {Exchange; Compute} while
(!TerminateCondition)`` with a **no-op** Compute and a single iteration
(/root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:27-31,92-95). Here
Compute is a real 5-point update (so benchmarks measure something), the
loop is a ``lax.scan`` (one compiled program for N steps, no per-step
dispatch), and the whole iteration is differentiable/jittable like any jax
code. A Pallas fused kernel variant lives in ops/stencil_kernel.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from tpuscratch.halo.exchange import HaloSpec, halo_exchange
from tpuscratch.halo.layout import TileLayout


def five_point(tile: jax.Array, layout: TileLayout, coeffs=(0.25, 0.25, 0.25, 0.25, 0.0)) -> jax.Array:
    """One Jacobi-style 5-point update of the core; halo is read, not
    written. ``coeffs`` = (north, south, west, east, center).

    Defaults to the Laplace/Jacobi average — the canonical workload for a
    halo benchmark.
    """
    hy, hx = layout.halo_y, layout.halo_x
    if hy < 1 or hx < 1:
        raise ValueError(f"five_point needs halo >= 1, got ({hy},{hx})")
    h, w = layout.core_h, layout.core_w
    cn, cs, cw, ce, cc = coeffs
    core = tile[hy : hy + h, hx : hx + w]
    north = tile[hy - 1 : hy - 1 + h, hx : hx + w]
    south = tile[hy + 1 : hy + 1 + h, hx : hx + w]
    west = tile[hy : hy + h, hx - 1 : hx - 1 + w]
    east = tile[hy : hy + h, hx + 1 : hx + 1 + w]
    new_core = cn * north + cs * south + cw * west + ce * east + cc * core
    return rebuild(tile, new_core, layout)


def rebuild(tile: jax.Array, new_core: jax.Array, layout: TileLayout) -> jax.Array:
    """Wrap a freshly-computed core back into the padded tile's border.

    By concatenation, NOT dynamic_update_slice: an in-place core update
    fused with overlapping shifted reads of the same buffer miscompiles on
    XLA:CPU under shard_map (Gauss-Seidel-like partial reads; even
    optimization_barrier does not prevent it — found by the steps=1 oracle
    test). Concat allocates a fresh buffer by construction and fuses just
    as well.
    """
    hy, hx = layout.halo_y, layout.halo_x
    h, w = layout.core_h, layout.core_w
    mid = jnp.concatenate(
        [tile[hy : hy + h, :hx], new_core, tile[hy : hy + h, hx + w :]], axis=1
    )
    return jnp.concatenate([tile[:hy], mid, tile[hy + h :]], axis=0)


def _compute(tile: jax.Array, layout: TileLayout, coeffs, impl: str) -> jax.Array:
    if impl == "xla":
        return five_point(tile, layout, coeffs)
    if impl == "pallas":
        from tpuscratch.ops.stencil_kernel import five_point_pallas

        return five_point_pallas(tile, layout, tuple(coeffs))
    raise ValueError(f"unknown stencil impl {impl!r}")


def stencil_step(tile: jax.Array, spec: HaloSpec, coeffs=(0.25, 0.25, 0.25, 0.25, 0.0), impl: str = "xla") -> jax.Array:
    """Exchange then compute — one iteration of the flagship loop.

    ``impl`` selects the compute path: 'xla' (fused by the compiler) or
    'pallas' (explicit VMEM kernel, ops/stencil_kernel.py) — the runtime
    analogue of the reference's compile-time GPU/CPU switch.
    """
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown stencil impl {impl!r}")
    tile = halo_exchange(tile, spec)
    return _compute(tile, spec.layout, coeffs, impl)


def run_stencil(tile: jax.Array, spec: HaloSpec, steps: int, coeffs=(0.25, 0.25, 0.25, 0.25, 0.0), impl: str = "xla") -> jax.Array:
    """N iterations as one compiled scan (SPMD: call inside shard_map)."""

    def body(t, _):
        return stencil_step(t, spec, coeffs, impl), ()

    out, _ = lax.scan(body, tile, None, length=steps)
    return out
