"""2D domain-decomposition with ghost-cell (halo) exchange — the flagship.

TPU-native redesign of the reference's only true library, the header-only
templated halo exchanger ``stencil2D.h`` (SURVEY.md §2.4). The shape of the
design survives — separate layout description, pure region geometry, a
precompiled per-direction transfer plan, one executor — but every piece is
re-grounded in XLA:

- ``Array2D``/``Array2DAccessor`` (layout over raw pointers) ->
  ``TileLayout``: a value object describing core extent + halo widths; the
  "accessor" is array slicing via ``SubarraySpec``.
- ``MPI_Type_create_subarray`` per region -> ``SubarraySpec`` slices
  (tpuscratch.dtypes); XLA fuses the gather/scatter into the transfer.
- ``CreateSendRecvArrays`` (8 send + 8 recv descriptors)
  -> ``HaloSpec.plan()``: 8 (send-region, recv-region, permutation)
  triples; the mirrored region/direction/tag tables collapse because a
  ppermute names source AND destination in one table.
- ``ExchangeData`` (Irecv/Isend/Waitall) -> ``halo_exchange``: 8
  ``ppermute``s whose scheduling/overlap is XLA's job.
- periodic cartesian communicator -> ``CartTopology`` permutation tables;
  corner (diagonal) neighbors are a single diagonal ppermute over the
  tuple of mesh axes, not two composed axis shifts.
"""

from tpuscratch.halo.layout import Region, TileLayout, sub_region  # noqa: F401
from tpuscratch.halo.exchange import HaloSpec, halo_exchange  # noqa: F401
from tpuscratch.halo.stencil import five_point, nine_point, stencil_step  # noqa: F401
from tpuscratch.halo.halo3d import (  # noqa: F401
    HaloSpec3D,
    TileLayout3D,
    distributed_stencil3d,
    halo_exchange3d,
    stencil_step3d,
)
