"""Distributed conjugate gradient on the 2D 5-point Laplacian.

The two communication patterns the reference builds — ghost-cell exchange
(/root/reference/stencil2d/stencil2D.h:363-377) and the allreduced dot
product (/root/reference/mpicuda2.cu:293) — are exactly the two
primitives a distributed Krylov solver needs: the matvec is a halo
exchange + local stencil application, and every inner product is a global
``psum``. The reference never takes that step (its ``Compute`` is a no-op
placeholder); this module does, as one compiled ``shard_map`` program with
the whole iteration inside a ``lax.while_loop`` — no host round trips
between iterations, unlike an MPI CG whose every dot product is a
blocking ``MPI_Allreduce`` on the host path.

Operator convention: ``A u = 4 u - u_up - u_down - u_left - u_right`` with
zero-Dirichlet boundaries — the (negated, unit-spacing) 5-point Laplacian,
symmetric positive definite, so plain CG applies. Dirichlet ghosts cost
nothing: the topology is open (non-periodic), and the exchange's
MPI_PROC_NULL semantics (halo/exchange.py) keep whatever ghost values the
tile already has — zeros, because the matvec embeds the core into a
zeroed padded tile each application.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.halo.exchange import HaloSpec, halo_exchange
from tpuscratch.halo.layout import TileLayout


def dirichlet_laplacian(core: jnp.ndarray, spec: HaloSpec) -> jnp.ndarray:
    """``A @ core`` for the zero-Dirichlet 5-point Laplacian, shard-local.

    ``core`` is this rank's (core_h, core_w) tile of the global vector
    (laid out as a 2D grid). One halo exchange fills the distance-1
    neighbor strips; open boundaries stay zero.
    """
    lay = spec.layout
    if (lay.halo_y, lay.halo_x) != (1, 1):
        raise ValueError(f"5-point operator needs halo (1,1), got layout {lay}")
    if spec.neighbors != 4:
        raise ValueError("use neighbors=4: corner transfers are dead weight here")
    padded = jnp.zeros(lay.padded_shape, core.dtype)
    padded = lax.dynamic_update_slice(padded, core, (1, 1))
    u = halo_exchange(padded, spec)
    return (
        4.0 * u[1:-1, 1:-1]
        - u[:-2, 1:-1]
        - u[2:, 1:-1]
        - u[1:-1, :-2]
        - u[1:-1, 2:]
    )


def cg(
    matvec: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    axes,
    *,
    tol: float = 1e-5,
    max_iters: int = 1000,
    precond: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = None,
):
    """(Preconditioned) conjugate gradient for SPD ``matvec``, SPMD over
    mesh ``axes``.

    Call inside ``shard_map``: ``b`` is the local shard, ``matvec`` maps a
    local shard to a local shard (doing its own neighbor communication),
    and inner products are summed with ``psum`` over ``axes``. Runs until
    ``||r|| <= tol * ||b||`` or ``max_iters``, entirely inside one
    ``lax.while_loop``. ``precond``, when given, applies an SPD
    approximation of ``A^-1`` (e.g. one multigrid V-cycle —
    solvers.multigrid.pcg_poisson_solve wires that up); convergence is
    still measured on the TRUE residual, so a bad preconditioner costs
    iterations, never correctness.

    Returns ``(x, iters, relres)`` — the local solution shard, iterations
    taken, and the achieved relative residual norm (replicated scalars).
    """
    dtype = b.dtype

    def gdot(u, v):
        return lax.psum(jnp.sum(u * v), axes)

    def rz_rs(r, z):
        """(r.z, r.r) as ONE stacked collective, UNCONDITIONALLY — the
        mpicuda2-4 discipline (fold scalars into one reduction) applied
        to both variants: the preconditioned loop would otherwise pay a
        third all-reduce latency per iteration, and the plain loop keeps
        the same single-psum schedule (the redundant r.z=r.r lane costs
        one local multiply, never a collective).  The ledger pins the
        count: classic CG is exactly TWO all-reduces per iteration
        (p.Ap is data-dependent on this one and cannot fold — the gap
        pipelined_cg closes)."""
        both = lax.psum(jnp.stack([jnp.sum(r * z), jnp.sum(r * r)]), axes)
        return both[0], both[1]

    x0 = jnp.zeros_like(b)
    z0 = b if precond is None else precond(b)
    rz0, rs0 = rz_rs(b, z0)       # rs is the TRUE residual stop rule
    stop2 = jnp.asarray(tol, dtype) ** 2 * rs0

    def cond(st):
        _, _, _, _, rs, k = st
        return jnp.logical_and(k < max_iters, rs > stop2)

    def body(st):
        x, r, p, rz, _, k = st
        ap = matvec(p)
        alpha = rz / gdot(p, ap)
        x = x + alpha * p
        r = r - alpha * ap
        z = r if precond is None else precond(r)
        rz_new, rs_new = rz_rs(r, z)
        p = z + (rz_new / rz) * p
        return (x, r, p, rz_new, rs_new, k + 1)

    x, _, _, _, rs, k = lax.while_loop(
        cond, body, (x0, b, z0, rz0, rs0, jnp.asarray(0, jnp.int32))
    )
    tiny = jnp.asarray(np.finfo(np.dtype(dtype)).tiny, dtype)
    return x, k, jnp.sqrt(rs / jnp.maximum(rs0, tiny))


def pipelined_cg(
    matvec: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    axes,
    *,
    tol: float = 1e-5,
    max_iters: int = 1000,
    precond: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = None,
    replace_every: int = 96,
):
    """Ghysels–Vanroose pipelined CG: ONE stacked ``psum`` per iteration.

    Same contract as :func:`cg` (``(x, iters, relres)``, SPD ``matvec``
    inside ``shard_map``), different recurrence structure: auxiliary
    vectors ``w = A u``, ``z = A q`` etc. are carried so that the three
    scalars an iteration needs — ``gamma = r.u``, ``delta = w.u``, and
    ``r.r`` for the stop rule — are all products of ALREADY-AVAILABLE
    vectors and fold into a single length-3 stacked ``psum``.  Classic
    CG cannot do this: ``p.Ap`` depends on the ``beta`` that the
    previous reduction produced, forcing two serialized collectives per
    iteration.  This is the mpicuda2-4 progression (separate dots ->
    timed spans -> one fused reduction, mpicuda4.cu:157-185) taken to
    its limit at the collective-schedule level — the same
    collective-decomposition discipline as Wang et al.'s overlap work,
    applied to latency instead of bandwidth.

    The price (the reason classic CG stays the default): two extra
    vector recurrences' worth of FLOPs and storage, and ALL state is
    maintained by recurrence — in f32 the joint drift of the auxiliary
    vectors stalls convergence on ill-conditioned systems, so every
    ``replace_every`` iterations the residual chain is REFRESHED from
    its definition (``r = b - Ax``, ``u = Mr``, ``w = Au``) and the
    next iteration RESTARTS the Krylov process (``beta = 0``) — each
    segment is genuine CG warm-started from the refreshed true
    residual, the restarted form of Ghysels & Vanroose's
    residual-replacement remedy (splicing a replaced residual into
    live conjugacy recurrences can break convergence; a restart cannot).
    The refresh is matvec-only (no collectives beyond the matvec's own
    halo ppermutes, NO extra psum — the one-reduction-per-iteration
    claim is unchanged), costs 2 matvecs once per segment, and fires
    inside a ``lax.cond`` whose predicate is replicated (every rank
    takes the same branch, so the collective schedule stays uniform).
    Convergence is tolerance-gated against classic CG in the tests
    rather than asserted bit-equal: the restart discards Krylov
    history, so the iteration count carries a conditioning-dependent
    penalty over classic CG (~1.1x at the config-15 64^2 geometry,
    growing on harder systems) — the per-iteration collective saving
    must beat it, which is the latency-bound-slice regime (one psum
    launch per iteration where classic pays two serialized), not the
    single-host one.  Classic CG stays the default.
    ``precond`` must be SPD, exactly as for :func:`cg`.
    """
    dtype = b.dtype
    apply_m = (lambda v: v) if precond is None else precond

    def fused3(r, u, w):
        """(r.u, w.u, r.r) — THE one collective per iteration."""
        out = lax.psum(
            jnp.stack([jnp.sum(r * u), jnp.sum(w * u), jnp.sum(r * r)]),
            axes,
        )
        return out[0], out[1], out[2]

    x0 = jnp.zeros_like(b)
    r0 = b
    u0 = apply_m(r0)
    w0 = matvec(u0)
    gamma0, delta0, rs0 = fused3(r0, u0, w0)
    stop2 = jnp.asarray(tol, dtype) ** 2 * rs0
    zero_v = jnp.zeros_like(b)
    one = jnp.asarray(1.0, dtype)

    def cond(st):
        rs, k = st[10], st[13]
        return jnp.logical_and(k < max_iters, rs > stop2)

    def body(st):
        (x, r, u, w, zv, q, s, p, gamma, delta, rs,
         gamma_prev, alpha_prev, k) = st
        m = apply_m(w)
        n = matvec(m)
        # a segment start (k = 0 or just-refreshed state) restarts the
        # Krylov process: beta = 0 discards the stale direction history,
        # so each segment is genuine CG warm-started from the refreshed
        # TRUE residual — monotone by construction, where splicing a
        # replaced residual into live conjugacy recurrences is not
        first = (k % replace_every) == 0
        beta = jnp.where(first, jnp.zeros((), dtype), gamma / gamma_prev)
        denom = jnp.where(first, delta,
                          delta - beta * gamma / alpha_prev)
        alpha = gamma / denom
        zv = n + beta * zv
        q = m + beta * q
        s = w + beta * s
        p = u + beta * p
        x = x + alpha * p
        r = r - alpha * s
        u = u - alpha * q
        w = w - alpha * zv

        def refresh(x_r):
            r_r = b - matvec(x_r)
            u_r = apply_m(r_r)
            return (r_r, u_r, matvec(u_r))

        r, u, w = lax.cond(
            (k + 1) % replace_every == 0,
            refresh,
            lambda x_r: (r, u, w),
            x,
        )
        gamma_n, delta_n, rs_n = fused3(r, u, w)
        return (x, r, u, w, zv, q, s, p, gamma_n, delta_n, rs_n,
                gamma, alpha, k + 1)

    st = (x0, r0, u0, w0, zero_v, zero_v, zero_v, zero_v,
          gamma0, delta0, rs0, one, one, jnp.asarray(0, jnp.int32))
    st = lax.while_loop(cond, body, st)
    x, rs, k = st[0], st[10], st[13]
    tiny = jnp.asarray(np.finfo(np.dtype(dtype)).tiny, dtype)
    return x, k, jnp.sqrt(rs / jnp.maximum(rs0, tiny))


#: poisson_solve method name -> solver loop
METHODS = {"cg": cg, "pipelined": pipelined_cg}


@functools.lru_cache(maxsize=64)
def _poisson_program(mesh: Mesh, spec, tol: float, iters: int,
                     method: str = "cg"):
    """Compiled-per-config CG program: repeat solves with the same mesh,
    layout, and knobs reuse the jitted program instead of re-tracing
    (~10 s of recompilation per 1024^2 solve otherwise)."""
    solver = METHODS[method]

    def local(b_tile):
        x, k, relres = solver(
            lambda p: dirichlet_laplacian(p, spec),
            b_tile[0, 0],
            tuple(mesh.axis_names),
            tol=tol,
            max_iters=iters,
        )
        return x[None, None], k, relres

    return run_spmd(
        mesh,
        local,
        P(*mesh.axis_names, None, None),
        (P(*mesh.axis_names, None, None), P(), P()),
    )


def poisson_solve(
    b_world: np.ndarray,
    mesh: Optional[Mesh] = None,
    *,
    tol: float = 1e-5,
    max_iters: Optional[int] = None,
    method: str = "cg",
):
    """Solve ``A x = b`` (zero-Dirichlet 5-point Laplacian) distributed.

    Whole-grid driver in the style of ``halo.driver``: decompose ``b``
    over a 2D device mesh, run the compiled CG program, reassemble.
    Returns ``(x_world, iters, relres)``.  ``method='pipelined'``
    selects the single-reduction Ghysels–Vanroose loop
    (:func:`pipelined_cg`) — one ``psum`` per iteration instead of two.
    """
    from tpuscratch.halo.driver import _setup, assemble, decompose

    if method not in METHODS:
        raise ValueError(f"unknown method {method!r}; have {tuple(METHODS)}")
    gh, gw = b_world.shape
    mesh, topo, layout, spec = _setup(
        b_world.shape, mesh, (1, 1), periodic=False, neighbors=4
    )
    iters = max_iters if max_iters is not None else gh * gw
    program = _poisson_program(mesh, spec, float(tol), int(iters), method)
    # CG state vectors are core tiles (no ghost ring): decompose/assemble
    # with a halo-0 view of the same layout
    flat = TileLayout(layout.core_h, layout.core_w, 0, 0)
    x_tiles, k, relres = program(jnp.asarray(decompose(b_world, topo, flat)))
    return assemble(np.asarray(x_tiles), topo, flat), int(k), float(relres)


def laplacian_apply_np(x: np.ndarray) -> np.ndarray:
    """Numpy oracle for ``dirichlet_laplacian`` on the whole grid."""
    p = np.pad(x, 1)
    return 4.0 * x - p[:-2, 1:-1] - p[2:, 1:-1] - p[1:-1, :-2] - p[1:-1, 2:]
