"""Distributed conjugate gradient on the 2D 5-point Laplacian.

The two communication patterns the reference builds — ghost-cell exchange
(/root/reference/stencil2d/stencil2D.h:363-377) and the allreduced dot
product (/root/reference/mpicuda2.cu:293) — are exactly the two
primitives a distributed Krylov solver needs: the matvec is a halo
exchange + local stencil application, and every inner product is a global
``psum``. The reference never takes that step (its ``Compute`` is a no-op
placeholder); this module does, as one compiled ``shard_map`` program with
the whole iteration inside a ``lax.while_loop`` — no host round trips
between iterations, unlike an MPI CG whose every dot product is a
blocking ``MPI_Allreduce`` on the host path.

Operator convention: ``A u = 4 u - u_up - u_down - u_left - u_right`` with
zero-Dirichlet boundaries — the (negated, unit-spacing) 5-point Laplacian,
symmetric positive definite, so plain CG applies. Dirichlet ghosts cost
nothing: the topology is open (non-periodic), and the exchange's
MPI_PROC_NULL semantics (halo/exchange.py) keep whatever ghost values the
tile already has — zeros, because the matvec embeds the core into a
zeroed padded tile each application.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.halo.exchange import HaloSpec, halo_exchange
from tpuscratch.halo.layout import TileLayout


def dirichlet_laplacian(core: jnp.ndarray, spec: HaloSpec) -> jnp.ndarray:
    """``A @ core`` for the zero-Dirichlet 5-point Laplacian, shard-local.

    ``core`` is this rank's (core_h, core_w) tile of the global vector
    (laid out as a 2D grid). One halo exchange fills the distance-1
    neighbor strips; open boundaries stay zero.
    """
    lay = spec.layout
    if (lay.halo_y, lay.halo_x) != (1, 1):
        raise ValueError(f"5-point operator needs halo (1,1), got layout {lay}")
    if spec.neighbors != 4:
        raise ValueError("use neighbors=4: corner transfers are dead weight here")
    padded = jnp.zeros(lay.padded_shape, core.dtype)
    padded = lax.dynamic_update_slice(padded, core, (1, 1))
    u = halo_exchange(padded, spec)
    return (
        4.0 * u[1:-1, 1:-1]
        - u[:-2, 1:-1]
        - u[2:, 1:-1]
        - u[1:-1, :-2]
        - u[1:-1, 2:]
    )


def cg(
    matvec: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    axes,
    *,
    tol: float = 1e-5,
    max_iters: int = 1000,
    precond: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = None,
):
    """(Preconditioned) conjugate gradient for SPD ``matvec``, SPMD over
    mesh ``axes``.

    Call inside ``shard_map``: ``b`` is the local shard, ``matvec`` maps a
    local shard to a local shard (doing its own neighbor communication),
    and inner products are summed with ``psum`` over ``axes``. Runs until
    ``||r|| <= tol * ||b||`` or ``max_iters``, entirely inside one
    ``lax.while_loop``. ``precond``, when given, applies an SPD
    approximation of ``A^-1`` (e.g. one multigrid V-cycle —
    solvers.multigrid.pcg_poisson_solve wires that up); convergence is
    still measured on the TRUE residual, so a bad preconditioner costs
    iterations, never correctness.

    Returns ``(x, iters, relres)`` — the local solution shard, iterations
    taken, and the achieved relative residual norm (replicated scalars).
    """
    dtype = b.dtype

    def gdot(u, v):
        return lax.psum(jnp.sum(u * v), axes)

    def rz_rs(r, z):
        """(r.z, r.r) as ONE collective — the preconditioned loop would
        otherwise pay a third all-reduce latency per iteration."""
        if precond is None:
            rs = gdot(r, r)
            return rs, rs
        both = lax.psum(jnp.stack([jnp.sum(r * z), jnp.sum(r * r)]), axes)
        return both[0], both[1]

    x0 = jnp.zeros_like(b)
    z0 = b if precond is None else precond(b)
    rz0, rs0 = rz_rs(b, z0)       # rs is the TRUE residual stop rule
    stop2 = jnp.asarray(tol, dtype) ** 2 * rs0

    def cond(st):
        _, _, _, _, rs, k = st
        return jnp.logical_and(k < max_iters, rs > stop2)

    def body(st):
        x, r, p, rz, _, k = st
        ap = matvec(p)
        alpha = rz / gdot(p, ap)
        x = x + alpha * p
        r = r - alpha * ap
        z = r if precond is None else precond(r)
        rz_new, rs_new = rz_rs(r, z)
        p = z + (rz_new / rz) * p
        return (x, r, p, rz_new, rs_new, k + 1)

    x, _, _, _, rs, k = lax.while_loop(
        cond, body, (x0, b, z0, rz0, rs0, jnp.asarray(0, jnp.int32))
    )
    tiny = jnp.asarray(np.finfo(np.dtype(dtype)).tiny, dtype)
    return x, k, jnp.sqrt(rs / jnp.maximum(rs0, tiny))


@functools.lru_cache(maxsize=64)
def _poisson_program(mesh: Mesh, spec, tol: float, iters: int):
    """Compiled-per-config CG program: repeat solves with the same mesh,
    layout, and knobs reuse the jitted program instead of re-tracing
    (~10 s of recompilation per 1024^2 solve otherwise)."""
    def local(b_tile):
        x, k, relres = cg(
            lambda p: dirichlet_laplacian(p, spec),
            b_tile[0, 0],
            tuple(mesh.axis_names),
            tol=tol,
            max_iters=iters,
        )
        return x[None, None], k, relres

    return run_spmd(
        mesh,
        local,
        P(*mesh.axis_names, None, None),
        (P(*mesh.axis_names, None, None), P(), P()),
    )


def poisson_solve(
    b_world: np.ndarray,
    mesh: Optional[Mesh] = None,
    *,
    tol: float = 1e-5,
    max_iters: Optional[int] = None,
):
    """Solve ``A x = b`` (zero-Dirichlet 5-point Laplacian) distributed.

    Whole-grid driver in the style of ``halo.driver``: decompose ``b``
    over a 2D device mesh, run the compiled CG program, reassemble.
    Returns ``(x_world, iters, relres)``.
    """
    from tpuscratch.halo.driver import _setup, assemble, decompose

    gh, gw = b_world.shape
    mesh, topo, layout, spec = _setup(
        b_world.shape, mesh, (1, 1), periodic=False, neighbors=4
    )
    iters = max_iters if max_iters is not None else gh * gw
    program = _poisson_program(mesh, spec, float(tol), int(iters))
    # CG state vectors are core tiles (no ghost ring): decompose/assemble
    # with a halo-0 view of the same layout
    flat = TileLayout(layout.core_h, layout.core_w, 0, 0)
    x_tiles, k, relres = program(jnp.asarray(decompose(b_world, topo, flat)))
    return assemble(np.asarray(x_tiles), topo, flat), int(k), float(relres)


def laplacian_apply_np(x: np.ndarray) -> np.ndarray:
    """Numpy oracle for ``dirichlet_laplacian`` on the whole grid."""
    p = np.pad(x, 1)
    return 4.0 * x - p[:-2, 1:-1] - p[2:, 1:-1] - p[1:-1, :-2] - p[1:-1, 2:]
