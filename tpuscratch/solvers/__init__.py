"""Distributed solvers built from the framework's primitives.

The reference stops at the mechanics — halo exchange with a no-op
``Compute`` (/root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:27) and
a distributed dot product (/root/reference/mpicuda2.cu) — and never
composes them into an algorithm. This package is the composition: a
conjugate-gradient Poisson solver whose matvec is the halo-exchanged
5-point operator and whose inner products are the psum dot product, i.e.
both reference flagships in one loop — its spectral sibling, the periodic
Poisson solve by distributed FFT diagonalization — and geometric
multigrid, the O(1)-cycle solver built from halo-exchanged smoothing and
local inter-level transfers.

The composition is also communication-avoiding and production-operated:
``pipelined_cg`` is the Ghysels–Vanroose single-reduction loop (ONE
stacked psum per iteration where classic CG pays two),
``mg_poisson3d_solve(s_step=...)`` folds ``s_step`` smoothing sweeps
into each deep halo exchange (the trapezoid scheme of the 2D stencil
library, applied to solvers), and ``solvers.runner`` drives long solves
through the trainer/halo-driver chunk loop — checkpointed, chaos-tested,
supervised, goodput-accounted.
"""

from tpuscratch.solvers.cg import (
    cg,
    dirichlet_laplacian,
    pipelined_cg,
    poisson_solve,
)
from tpuscratch.solvers.multigrid import (
    mg_poisson_solve,
    pcg_poisson_solve,
    v_cycle,
)
from tpuscratch.solvers.multigrid3d import (
    mg_poisson3d_solve,
    pcg_poisson3d_solve,
    v_cycle3,
)
from tpuscratch.solvers.runner import (
    SolveReport,
    checkpointed_mg3d_solve,
    supervised_mg3d_solve,
)
from tpuscratch.solvers.spectral import (
    periodic_poisson3d_fft,
    periodic_poisson_fft,
)

__all__ = [
    "cg",
    "pipelined_cg",
    "dirichlet_laplacian",
    "poisson_solve",
    "mg_poisson_solve",
    "mg_poisson3d_solve",
    "pcg_poisson_solve",
    "pcg_poisson3d_solve",
    "v_cycle",
    "v_cycle3",
    "SolveReport",
    "checkpointed_mg3d_solve",
    "supervised_mg3d_solve",
    "periodic_poisson3d_fft",
    "periodic_poisson_fft",
]
