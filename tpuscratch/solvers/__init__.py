"""Distributed solvers built from the framework's primitives.

The reference stops at the mechanics — halo exchange with a no-op
``Compute`` (/root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:27) and
a distributed dot product (/root/reference/mpicuda2.cu) — and never
composes them into an algorithm. This package is the composition: a
conjugate-gradient Poisson solver whose matvec is the halo-exchanged
5-point operator and whose inner products are the psum dot product, i.e.
both reference flagships in one loop — its spectral sibling, the periodic
Poisson solve by distributed FFT diagonalization — and geometric
multigrid, the O(1)-cycle solver built from halo-exchanged smoothing and
local inter-level transfers.
"""

from tpuscratch.solvers.cg import cg, dirichlet_laplacian, poisson_solve
from tpuscratch.solvers.multigrid import (
    mg_poisson_solve,
    pcg_poisson_solve,
    v_cycle,
)
from tpuscratch.solvers.multigrid3d import (
    mg_poisson3d_solve,
    pcg_poisson3d_solve,
    v_cycle3,
)
from tpuscratch.solvers.spectral import (
    periodic_poisson3d_fft,
    periodic_poisson_fft,
)

__all__ = [
    "cg",
    "dirichlet_laplacian",
    "poisson_solve",
    "mg_poisson_solve",
    "mg_poisson3d_solve",
    "pcg_poisson_solve",
    "pcg_poisson3d_solve",
    "v_cycle",
    "v_cycle3",
    "periodic_poisson3d_fft",
    "periodic_poisson_fft",
]
