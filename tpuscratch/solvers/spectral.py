"""Spectral (FFT) solver for the periodic Poisson problem.

Companion to the CG solver (solvers/cg.py): where CG iterates
halo-exchange matvecs until the residual dies, the spectral method
diagonalizes the periodic 5-point Laplacian in ONE distributed FFT round
trip — two all_to_all transposes and a pointwise eigenvalue divide. The
periodic operator (the boundary condition of the reference's flagship
stencil run, /root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:49-52)
is singular on the constant mode, so the solve projects it out and
returns the unique zero-mean solution.

Eigenvalues: the 5-point operator ``A u = 4u - u_N - u_S - u_W - u_E``
with periodic wrap has DFT eigenvalues
``lam(k, l) = 4 - 2 cos(2 pi k / H) - 2 cos(2 pi l / W)``.

Two transform backends (parallel/fft.py): ``impl='xla'`` uses complex64
``jnp.fft``; ``impl='dft'`` uses the matmul-form DFT on (re, im) float32
planes — required on TPU runtimes with no complex support (this repo's
tunnel backend), and an MXU workload in its own right. ``'auto'`` probes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.parallel.fft import (
    complex_supported,
    fft2_sharded,
    fft2_sharded_pair,
    fft3_sharded,
    fft3_sharded_pair,
    ifft2_from_pencil,
    ifft2_from_pencil_pair,
    ifft3_from_pencil,
    ifft3_from_pencil_pair,
)
from tpuscratch.runtime.mesh import make_mesh_1d


def periodic_laplacian_np(x: np.ndarray) -> np.ndarray:
    """Numpy oracle: periodic 5-point operator (positive-semidefinite)."""
    return (
        4.0 * x
        - np.roll(x, 1, 0) - np.roll(x, -1, 0)
        - np.roll(x, 1, 1) - np.roll(x, -1, 1)
    )


def periodic_poisson_fft(
    b_world: np.ndarray, mesh: Optional[Mesh] = None, impl: str = "auto"
):
    """Solve ``A x = b - mean(b)`` for the periodic 5-point Laplacian.

    Rows of the grid are sharded over a 1D mesh (default: all devices).
    Returns the zero-mean ``x_world``; residual is machine precision, not
    iterative — there is no tolerance knob.
    """
    if impl == "auto":
        impl = "xla" if complex_supported() else "dft"
    if impl not in ("xla", "dft"):
        raise ValueError(f"impl must be auto|xla|dft, got {impl!r}")
    mesh = mesh if mesh is not None else make_mesh_1d("x")
    (ax,) = mesh.axis_names
    n = mesh.devices.size
    gh, gw = b_world.shape
    if gh % n or gw % n:
        raise ValueError(
            f"grid {b_world.shape} needs both dims divisible by the "
            f"{n}-device mesh (rows for the shard, cols for the transpose)"
        )

    program = _spectral_program(mesh, ax, n, gh, gw, impl)
    return np.asarray(program(jnp.asarray(b_world)))


@functools.lru_cache(maxsize=32)
def _spectral_program(mesh, ax, n, gh, gw, impl):
    """Compiled-per-config spectral solver (repeat solves skip re-trace)."""
    def inv_eigenvalues(d):
        k = jnp.arange(gh, dtype=jnp.float32)
        l = d * (gw // n) + jnp.arange(gw // n, dtype=jnp.float32)
        # sin^2 form: no cancellation in f32 (the 4 - 2cos - 2cos form
        # loses the small eigenvalues to rounding), and singular exactly
        # and only at the k=l=0 constant mode — no threshold needed
        lam = (
            4.0 * jnp.sin(jnp.pi * k / gh)[:, None] ** 2
            + 4.0 * jnp.sin(jnp.pi * l / gw)[None, :] ** 2
        )
        singular = (k == 0)[:, None] & (l == 0)[None, :]
        return jnp.where(singular, 0.0, 1.0 / jnp.where(singular, 1.0, lam))

    def local(b):
        inv = inv_eigenvalues(lax.axis_index(ax))
        if impl == "dft":
            re, im = fft2_sharded_pair(
                b, jnp.zeros_like(b), ax, restore_layout=False
            )
            re, _ = ifft2_from_pencil_pair(re * inv, im * inv, ax)
            return re.astype(b.dtype)
        hat = fft2_sharded(b, ax, restore_layout=False)  # (gh, gw/n) pencil
        return jnp.real(ifft2_from_pencil(hat * inv, ax)).astype(b.dtype)

    return run_spmd(mesh, local, P(ax), P(ax))


def periodic_poisson3d_fft(
    b_world: np.ndarray, mesh: Optional[Mesh] = None, impl: str = "auto"
):
    """Solve ``A x = b - mean(b)`` for the periodic 7-point Laplacian —
    :func:`periodic_poisson_fft` one dimension up, over the 3D pencil
    FFT (`parallel.fft.fft3_sharded_pair`): z-slabs sharded on a 1D
    mesh, ONE all_to_all per transform direction, sin²-form eigenvalues
    ``4 sin²(πk/Z) + 4 sin²(πl/Y) + 4 sin²(πm/X)``. Direct (one round
    trip, machine-precision residual) where multigrid3d iterates — the
    two are cross-checked in tests. Same backend contract as the 2D
    solver: ``impl='xla'`` uses complex64 `jnp.fft`
    (`fft3_sharded`), ``'dft'`` the (re, im) pair path (required on
    complex-free TPU runtimes), ``'auto'`` picks by
    :func:`parallel.fft.complex_supported`."""
    if impl == "auto":
        impl = "xla" if complex_supported() else "dft"
    if impl not in ("dft", "xla"):
        raise ValueError(f"impl must be auto|xla|dft, got {impl!r}")
    mesh = mesh if mesh is not None else make_mesh_1d("x")
    (ax,) = mesh.axis_names
    n = mesh.devices.size
    gz, gy, gx = b_world.shape
    if gz % n or gy % n:
        raise ValueError(
            f"grid {b_world.shape} needs Z and Y divisible by the "
            f"{n}-device mesh (Z for the shard, Y for the transpose)"
        )
    program = _spectral3_program(mesh, ax, n, gz, gy, gx, impl)
    return np.asarray(program(jnp.asarray(b_world)))


@functools.lru_cache(maxsize=32)
def _spectral3_program(mesh, ax, n, gz, gy, gx, impl):
    def inv_eigenvalues(d):
        # pencil layout (X, Y/n, Z): kx full, ky this device's shard, kz full
        m = jnp.arange(gx, dtype=jnp.float32)
        l = d * (gy // n) + jnp.arange(gy // n, dtype=jnp.float32)
        k = jnp.arange(gz, dtype=jnp.float32)
        lam = (
            4.0 * jnp.sin(jnp.pi * m / gx)[:, None, None] ** 2
            + 4.0 * jnp.sin(jnp.pi * l / gy)[None, :, None] ** 2
            + 4.0 * jnp.sin(jnp.pi * k / gz)[None, None, :] ** 2
        )
        singular = (
            (m == 0)[:, None, None]
            & (l == 0)[None, :, None]
            & (k == 0)[None, None, :]
        )
        return jnp.where(singular, 0.0, 1.0 / jnp.where(singular, 1.0, lam))

    def local(b):
        inv = inv_eigenvalues(lax.axis_index(ax))
        if impl == "dft":
            re, im = fft3_sharded_pair(
                b, jnp.zeros_like(b), ax, restore_layout=False
            )
            re, _ = ifft3_from_pencil_pair(re * inv, im * inv, ax)
            return re.astype(b.dtype)
        hat = fft3_sharded(b, ax, restore_layout=False)  # (X, Y/n, Z)
        return jnp.real(ifft3_from_pencil(hat * inv, ax)).astype(b.dtype)

    return run_spmd(mesh, local, P(ax), P(ax))
