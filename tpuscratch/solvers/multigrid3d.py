"""Geometric multigrid for the 3D periodic Poisson problem.

The 2D solver (solvers/multigrid.py) one dimension up, built on the 3D
halo library: the 7-point operator and smoothers need only face ghosts,
but the trilinear transfer pair reads CORNER ghosts — the first consumer
of the 26-neighbor exchange (halo3d ``neighbors=26``). Same design
decisions as 2D, same reasons:

- every level reuses the same 3-axis device mesh with a halved tile;
- VPU-friendly smoothers (damped Jacobi / red-black GS via two fused
  masked half-updates, parity (i+j+k) mod 2 — global when core extents
  are even);
- adjoint transfers: trilinear prolongation and full-weighting
  restriction R = P^T/8 ([1,3,3,1]/8 tensor cubed), continuum scaling
  4 = (2h)^2/h^2 on the restricted residual (dimension-independent);
- spec PAIRS per level: the hot smoothing/residual exchanges use the
  faces-only plan (6 ppermutes), only the two inter-level transfers per
  cycle pay the 26-transfer plan;
- one trace: unrolled level recursion, while_loop cycle iteration,
  psum'd residuals, zero host round trips;
- communication-avoiding smoothing on request (``s_step > 1``): the
  s-step / trapezoid scheme — one deep axis-sequential exchange
  (``halo_exchange3d_seq``, 6 ppermutes at any depth) buys ``s`` Jacobi
  sweeps (ghost depth ``s``) or ``s`` red-black sweeps (depth ``2s``),
  bit-identical to exchange-every-sweep, clamped per level to what the
  tile seats.

Measured (tests assert the bounds): cycle count flat in grid size —
7-8 cycles to 1e-6 from 16^3 to 128^3 (chip-verified) — the same O(1)
behavior as 2D; MG-PCG (``pcg_poisson3d_solve``) needs 5-6 iterations.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.halo.halo3d import (
    HaloSpec3D,
    TileLayout3D,
    decompose3d_cores,
    assemble3d_cores,
    halo_exchange3d,
    halo_exchange3d_seq,
)
from tpuscratch.runtime.mesh import make_mesh, topology_of
from tpuscratch.runtime.topology import factor3d

_W4 = (0.125, 0.375, 0.375, 0.125)


def _padded3(core: jnp.ndarray, spec: HaloSpec3D) -> jnp.ndarray:
    """Embed a core tile and fill its 1-ghost shell from the torus."""
    p = jnp.zeros(spec.layout.padded_shape, core.dtype)
    p = lax.dynamic_update_slice(p, core, (1, 1, 1))
    return halo_exchange3d(p, spec)


def periodic_laplacian3(core: jnp.ndarray, spec: HaloSpec3D) -> jnp.ndarray:
    """``A @ core`` for the periodic 7-point operator (diagonal 6)."""
    u = _padded3(core, spec)
    return (
        6.0 * u[1:-1, 1:-1, 1:-1]
        - u[:-2, 1:-1, 1:-1] - u[2:, 1:-1, 1:-1]
        - u[1:-1, :-2, 1:-1] - u[1:-1, 2:, 1:-1]
        - u[1:-1, 1:-1, :-2] - u[1:-1, 1:-1, 2:]
    )


def _neighbor_sum3(u, spec: HaloSpec3D):
    p = _padded3(u, spec)
    return (
        p[:-2, 1:-1, 1:-1] + p[2:, 1:-1, 1:-1]
        + p[1:-1, :-2, 1:-1] + p[1:-1, 2:, 1:-1]
        + p[1:-1, 1:-1, :-2] + p[1:-1, 1:-1, 2:]
    )


def jacobi_smooth3(u, f, spec: HaloSpec3D, omega: float, sweeps: int):
    def body(_, u):
        return u + (omega / 6.0) * (f - periodic_laplacian3(u, spec))

    return lax.fori_loop(0, sweeps, body, u)


def rbgs_smooth3(u, f, spec: HaloSpec3D, sweeps: int, reverse: bool = False):
    """Red-black GS with (i+j+k) mod 2 parity (global for even cores)."""
    cz, cy, cx = spec.layout.core
    if cz % 2 or cy % 2 or cx % 2:
        raise ValueError(
            f"red-black smoothing needs even core extents, got {spec.layout.core}"
        )
    ii = jnp.arange(cz)[:, None, None]
    jj = jnp.arange(cy)[None, :, None]
    kk = jnp.arange(cx)[None, None, :]
    red = (ii + jj + kk) % 2 == 0
    first, second = (~red, red) if reverse else (red, ~red)

    def half(u, mask):
        return jnp.where(mask, (f + _neighbor_sum3(u, spec)) / 6.0, u)

    def body(_, u):
        return half(half(u, first), second)

    return lax.fori_loop(0, sweeps, body, u)


def jacobi_smooth3_stream(u, f, spec: HaloSpec3D, omega: float,
                          sweeps: int, depth: int = 4):
    """``sweeps`` damped-Jacobi sweeps via the deep-z STREAMED kernel
    (round 5): min(sweeps, depth) sweeps fold into each manual-DMA HBM
    pass — the solver layer finally riding the repo's fastest kernel
    (VERDICT r4 next #5).  The smoother is affine, u' = (1-omega) u +
    (omega/6) nbsum(u) + (omega/6) f, so the kernel takes coeffs
    ((omega/6) x 6, 1-omega) plus the rhs term streamed alongside
    (pre-ghosted once per smooth call — f is constant across sweeps).
    z-slab meshes only (the caller falls back to plain Jacobi
    elsewhere)."""
    from jax import lax as _lax

    from tpuscratch.ops.stencil_stream import seven_point_streamed_pallas

    topo = spec.topology
    if not all(topo.periodic):
        # the kernel's open_flags machinery is not threaded here (the
        # mg solvers are periodic-only); without it an open-z end's
        # ghost planes would evolve across folded substeps instead of
        # staying zero — refuse rather than smooth wrong
        raise ValueError(
            "jacobi_smooth3_stream is periodic-only; use jacobi_smooth3 "
            "for open boundaries"
        )
    cz, cy, cx = spec.layout.core
    coeffs = (omega / 6.0,) * 6 + (1.0 - omega,)
    wrap_z = topo.dims[0] == 1 and topo.periodic[0]

    def zghosts(c, d):
        if wrap_z:
            return c[cz - d :], c[:d]
        a_mz = _lax.ppermute(
            c[cz - d :], spec.axes, list(topo.send_permutation((1, 0, 0)))
        )
        a_pz = _lax.ppermute(
            c[:d], spec.axes, list(topo.send_permutation((-1, 0, 0)))
        )
        return a_mz, a_pz

    def ghosted_f(d):
        f_mz, f_pz = zghosts(f, d)
        return jnp.concatenate([f_mz, f, f_pz], axis=0)

    def one_pass(c, d, rhs):
        a_mz, a_pz = zghosts(c, d)
        return seven_point_streamed_pallas(
            c, a_mz, a_pz, (cz, cy, cx), coeffs, d,
            rhs=rhs, rhs_coeff=omega / 6.0,
        )

    k = min(depth, sweeps)
    q, r = divmod(sweeps, k)
    out = u
    if q:
        # f never changes across sweeps: ghost it ONCE for the q-loop
        rhs_k = ghosted_f(k)
        out = lax.fori_loop(0, q, lambda _, c: one_pass(c, k, rhs_k), out)
    if r:
        out = one_pass(out, r, ghosted_f(r))
    return out


def _deep_spec(spec: HaloSpec3D, depth: int) -> HaloSpec3D:
    """The depth-``depth`` twin of a level's faces spec (the s-step
    smoother's ghost geometry; plans are cached per (layout, topology))."""
    return HaloSpec3D(
        layout=TileLayout3D(spec.layout.core, (depth,) * 3),
        topology=spec.topology, axes=spec.axes, neighbors=6,
    )


def _embed_seq(core: jnp.ndarray, dspec: HaloSpec3D) -> jnp.ndarray:
    """Zero-embed a core tile at the deep spec's depth and fill the FULL
    ghost shell (edges/corners transitively) with the 6-ppermute
    axis-sequential exchange."""
    d = dspec.layout.halo[0]
    p = jnp.zeros(dspec.layout.padded_shape, core.dtype)
    p = lax.dynamic_update_slice(p, core, (d, d, d))
    return halo_exchange3d_seq(p, dspec)


def _require_periodic_deep(spec: HaloSpec3D, name: str) -> None:
    if not all(spec.topology.periodic):
        # an open physical end's ghost rings would need re-zeroing every
        # substep (the 2D deep path's open_side_flags machinery); the mg
        # solvers are periodic-only, so refuse rather than smooth wrong
        raise ValueError(f"{name} is periodic-only; use the per-sweep "
                         "smoother for open boundaries")


def jacobi_smooth3_deep(u, f, spec: HaloSpec3D, omega: float, sweeps: int,
                        s: int):
    """``sweeps`` damped-Jacobi sweeps, ``s`` per halo exchange — the
    s-step / trapezoid (ghost-zone) scheme of ``halo.stencil``'s
    ``run_stencil_deep``, one dimension up and fused with the rhs.

    One depth-``s`` axis-sequential exchange fills the full ghost shell;
    substep ``j`` then updates every cell at least ``j`` rings in from
    the padded border with EXACTLY the per-sweep arithmetic (same op
    order as :func:`jacobi_smooth3`, so the result is bit-identical —
    the trapezoid-validity law the tests pin).  The ledger-visible trade:
    ``ceil(sweeps/s)`` state exchanges plus ONE rhs ghost fill per call
    (depth ``s-1``; ``f`` never changes across sweeps) instead of one
    exchange per sweep — ~``s``x fewer ppermute launches, per-sweep wire
    bytes within an ``O(s/core)`` redundant-boundary factor of the
    per-sweep path.  Rounds are python-unrolled so the static collective
    count in the compiled HLO IS the dynamic launch count (the proof
    obligation), which keeps ``sweeps`` a trace-time constant.
    """
    _require_periodic_deep(spec, "jacobi_smooth3_deep")
    if s < 1:
        raise ValueError(f"s-step depth must be >= 1, got {s}")
    if s == 1:
        return jacobi_smooth3(u, f, spec, omega, sweeps)
    if s > min(spec.layout.core):
        raise ValueError(
            f"s={s} deeper than core {spec.layout.core}: neighbor slabs "
            "would overlap"
        )
    dspec = _deep_spec(spec, s)
    fp = _embed_seq(f, _deep_spec(spec, s - 1))

    def lap(a):
        # periodic_laplacian3's exact op order, on the shrinking window
        return (
            6.0 * a[1:-1, 1:-1, 1:-1]
            - a[:-2, 1:-1, 1:-1] - a[2:, 1:-1, 1:-1]
            - a[1:-1, :-2, 1:-1] - a[1:-1, 2:, 1:-1]
            - a[1:-1, 1:-1, :-2] - a[1:-1, 1:-1, 2:]
        )

    def trapezoid(core, k):
        a = _embed_seq(core, dspec)
        for j in range(1, k + 1):
            # substep j's output spans ghost ring s-j; the rhs tile is
            # ghosted to depth s-1, so crop j-1 rings to align
            c = j - 1
            fw = fp[c:-c, c:-c, c:-c] if c else fp
            a = a[1:-1, 1:-1, 1:-1] + (omega / 6.0) * (fw - lap(a))
        crop = s - k
        return a[crop:-crop, crop:-crop, crop:-crop] if crop else a

    q, r = divmod(sweeps, s)
    out = u
    for _ in range(q):
        out = trapezoid(out, s)
    if r:
        out = trapezoid(out, r)
    return out


def _parity_masks(shape, offset: int):
    ii = jnp.arange(shape[0])[:, None, None]
    jj = jnp.arange(shape[1])[None, :, None]
    kk = jnp.arange(shape[2])[None, None, :]
    red = (ii + jj + kk + offset) % 2 == 0
    return red


def rbgs_smooth3_deep(u, f, spec: HaloSpec3D, sweeps: int, s: int,
                      reverse: bool = False):
    """``sweeps`` red-black GS sweeps, ``s`` per halo exchange.

    Each RBGS sweep is TWO masked half-updates and the per-sweep path
    exchanges before each (12 ppermutes/sweep), so the trapezoid needs
    ghost depth ``2*s`` and wins ``2*s``x on launches: one 6-ppermute
    exchange per ``s`` sweeps plus one depth-``2s-1`` rhs fill per call.
    Masks use GLOBAL parity: even core extents make every rank's tile
    start even, so parity in window coordinates is rank-independent and
    only shifts by the crop count (odd per crop — 3 axes each advance
    one) — exactly the per-sweep smoother's (i+j+k) mod 2 coloring seen
    through the shrinking window.  Same op order as
    :func:`rbgs_smooth3`, so bit-identical (the tests pin it).
    """
    _require_periodic_deep(spec, "rbgs_smooth3_deep")
    cz, cy, cx = spec.layout.core
    if cz % 2 or cy % 2 or cx % 2:
        raise ValueError(
            f"red-black smoothing needs even core extents, got {spec.layout.core}"
        )
    if s < 1:
        raise ValueError(f"s-step depth must be >= 1, got {s}")
    d = 2 * s
    if d > min(spec.layout.core):
        raise ValueError(
            f"s={s} needs ghost depth {d} > core {spec.layout.core}"
        )
    dspec = _deep_spec(spec, d)
    fp = _embed_seq(f, _deep_spec(spec, d - 1))

    def nbsum(a):
        # _neighbor_sum3's exact op order, on the shrinking window
        return (
            a[:-2, 1:-1, 1:-1] + a[2:, 1:-1, 1:-1]
            + a[1:-1, :-2, 1:-1] + a[1:-1, 2:, 1:-1]
            + a[1:-1, 1:-1, :-2] + a[1:-1, 1:-1, 2:]
        )

    def trapezoid(core, k):
        # k sweeps = 2k half-updates; half t's output sits t+1 crops in,
        # so its window parity offset is (t+1) mod 2 (d is even, rank
        # starts even, each crop shifts i+j+k's parity by 3 == 1 mod 2)
        a = _embed_seq(core, dspec)
        for t in range(2 * k):
            # half t's output spans ghost ring d-t-1; the rhs tile is
            # ghosted to depth d-1, so crop t rings to align
            fw = fp[t:-t, t:-t, t:-t] if t else fp
            red = _parity_masks(
                tuple(n - 2 for n in a.shape), (t + 1) % 2
            )
            update_red = (t % 2 == 0) != reverse
            mask = red if update_red else ~red
            a = jnp.where(mask, (fw + nbsum(a)) / 6.0, a[1:-1, 1:-1, 1:-1])
        crop = d - 2 * k
        return a[crop:-crop, crop:-crop, crop:-crop] if crop else a

    q, r = divmod(sweeps, s)
    out = u
    for _ in range(q):
        out = trapezoid(out, s)
    if r:
        out = trapezoid(out, r)
    return out


def _stream_smoothable(spec: HaloSpec3D, sweeps: int) -> bool:
    """True when the streamed smoother serves this level: a z-slab
    periodic mesh, a core deep enough for >= 2 bands of >= the fold
    depth (the kernel's window structure), and a FULL-LANE-TILE plane
    width — chip-probed (round 5): the 3D streamed kernel family is a
    Mosaic remote-compile DNF for cx < 128 on silicon (sub-lane-tile
    planes; the CPU interpreter accepts them), so only the finest
    levels stream and coarser levels use plain Jacobi — which is also
    where the fold buys nothing (coarse sweeps are launch-bound, not
    HBM-bound)."""
    topo = spec.topology
    cz = spec.layout.core[0]
    k = min(4, sweeps)
    return (
        topo.dims[1] == 1 and topo.dims[2] == 1
        and all(topo.periodic)
        and cz >= 2 * k
        and spec.layout.core[1] >= 8 and spec.layout.core[2] >= 128
    )


def _smooth3(u, f, spec, omega, sweeps, smoother, reverse=False,
             s_step: int = 1):
    """One smoothing pass; ``s_step > 1`` requests the s-step deep-halo
    variants (s sweeps per exchange), clamped per level to what the tile
    supports — coarse levels whose cores cannot seat the ghost depth
    fall back to the per-sweep path, which is also where the fold buys
    least (coarse sweeps are launch-bound on tiny arrays either way)."""
    cz, cy, cx = spec.layout.core
    if smoother == "jacobi-stream":
        if _stream_smoothable(spec, sweeps):
            return jacobi_smooth3_stream(u, f, spec, omega, sweeps)
        return jacobi_smooth3(u, f, spec, omega, sweeps)
    deep = (
        s_step > 1
        and all(spec.topology.periodic)
        and sweeps > 1
    )
    if smoother == "rbgs" and not (cz % 2 or cy % 2 or cx % 2):
        if deep:
            s_eff = min(s_step, sweeps, min(cz, cy, cx) // 2)
            if s_eff > 1:
                return rbgs_smooth3_deep(u, f, spec, sweeps, s_eff, reverse)
        return rbgs_smooth3(u, f, spec, sweeps, reverse)
    if smoother not in ("jacobi", "rbgs"):
        raise ValueError(f"unknown smoother {smoother!r}")
    if deep:
        s_eff = min(s_step, sweeps, min(cz, cy, cx))
        if s_eff > 1:
            return jacobi_smooth3_deep(u, f, spec, omega, sweeps, s_eff)
    return jacobi_smooth3(u, f, spec, omega, sweeps)


def restrict_fw3(r: jnp.ndarray, spec: HaloSpec3D) -> jnp.ndarray:
    """Full-weighting restriction: the [1,3,3,1]/8 stencil cubed over each
    coarse cell's 4x4x4 fine neighborhood — reads EDGE and CORNER ghosts,
    so ``spec`` must carry the 26-neighbor plan."""
    rp = _padded3(r, spec)
    cz, cy, cx = (s // 2 for s in r.shape)
    acc = jnp.zeros((cz, cy, cx), r.dtype)
    for a, wa in enumerate(_W4):
        for b, wb in enumerate(_W4):
            for c, wc in enumerate(_W4):
                acc = acc + wa * wb * wc * lax.slice(
                    rp, (a, b, c),
                    (a + 2 * cz - 1, b + 2 * cy - 1, c + 2 * cx - 1),
                    (2, 2, 2),
                )
    return acc


def prolong_trilinear(e: jnp.ndarray, spec: HaloSpec3D) -> jnp.ndarray:
    """Cell-centered trilinear prolongation: each fine cell blends its 8
    nearest coarse cells with (3/4, 1/4) per-axis weights (corner ghosts
    again — 26-neighbor spec)."""
    ep = _padded3(e, spec)
    cz, cy, cx = e.shape

    def sl(dz, dy, dx):
        return ep[1 + dz:1 + dz + cz, 1 + dy:1 + dy + cy, 1 + dx:1 + dx + cx]

    octants = []
    for a in (0, 1):          # fine z within the coarse cell
        planes = []
        for b in (0, 1):      # fine y
            rows = []
            for c in (0, 1):  # fine x
                sz = -1 if a == 0 else 1
                sy = -1 if b == 0 else 1
                sx = -1 if c == 0 else 1
                v = (
                    27 * sl(0, 0, 0)
                    + 9 * (sl(sz, 0, 0) + sl(0, sy, 0) + sl(0, 0, sx))
                    + 3 * (sl(sz, sy, 0) + sl(sz, 0, sx) + sl(0, sy, sx))
                    + sl(sz, sy, sx)
                ) / 64.0
                rows.append(v)
            planes.append(jnp.stack(rows, axis=-1).reshape(cz, cy, 2 * cx))
        stacked = jnp.stack(planes, axis=2).reshape(cz, 2 * cy, 2 * cx)
        octants.append(stacked)
    return jnp.stack(octants, axis=1).reshape(2 * cz, 2 * cy, 2 * cx)


def level_specs3(
    layout: TileLayout3D, topo, axes, levels: int
) -> list[tuple[HaloSpec3D, HaloSpec3D]]:
    """Per level, a (faces-only, all-26) spec PAIR: smoothing and the
    residual are 7-point and pay only 6 ppermutes per exchange in the hot
    loop; the two inter-level transfers read edge/corner ghosts and use
    the 26-plan (the 2D solver's neighbors=4 split, one dimension up)."""
    specs = []
    for l in range(levels):
        core = tuple(c >> l for c in layout.core)
        if any(c < 1 for c in core) or (
            l < levels - 1 and any(c % 2 for c in core)
        ):
            raise ValueError(
                f"tile {layout.core} does not support {levels} levels "
                f"(level {l} would be {core})"
            )
        lay = TileLayout3D(core, (1, 1, 1))
        specs.append(tuple(
            HaloSpec3D(layout=lay, topology=topo, axes=axes, neighbors=n)
            for n in (6, 26)
        ))
    return specs


def v_cycle3(
    u, f, specs, level: int = 0,
    nu: int = 2, coarse_sweeps: int = 32, omega: float = 6 / 7,
    smoother: str = "rbgs", s_step: int = 1,
):
    """One 3D V-cycle (recursion unrolls at trace time); post-smoothing
    reverses color order so the cycle is symmetric. ``specs`` is the
    ``level_specs3`` list of (faces, all-26) pairs.  ``s_step > 1`` runs
    every smoothing pass communication-avoiding: ``s_step`` sweeps per
    (deep, axis-sequential) halo exchange.  Each smoothing pass is
    BIT-identical to its per-sweep twin (tests assert it); the composed
    cycle agrees to roundoff (whole-program fusion may re-round) at an
    identical cycle count."""
    s6, s26 = specs[level]
    if level == len(specs) - 1:
        half = (coarse_sweeps + 1) // 2
        u = _smooth3(u, f, s6, omega, half, smoother, s_step=s_step)
        return _smooth3(u, f, s6, omega, half, smoother, reverse=True,
                        s_step=s_step)
    u = _smooth3(u, f, s6, omega, nu, smoother, s_step=s_step)
    r = f - periodic_laplacian3(u, s6)
    rc = 4.0 * restrict_fw3(r, s26)
    ec = v_cycle3(
        jnp.zeros_like(rc), rc, specs, level + 1, nu, coarse_sweeps, omega,
        smoother, s_step,
    )
    u = u + prolong_trilinear(ec, specs[level + 1][1])
    return _smooth3(u, f, s6, omega, nu, smoother, reverse=True,
                    s_step=s_step)


def _mg_prologue3(b_world: np.ndarray, mesh: Optional[Mesh], levels: Optional[int]):
    """Shared 3D driver prologue (the 2D _mg_prologue one dimension up):
    default mesh, divisibility check, per-level spec pairs."""
    import jax

    if mesh is None:
        mesh = make_mesh(factor3d(len(jax.devices())), ("z", "row", "col"))
    dims = tuple(mesh.devices.shape)
    topo = topology_of(mesh, periodic=True)
    if any(w % d for w, d in zip(b_world.shape, dims)):
        raise ValueError(f"grid {b_world.shape} not divisible by mesh {dims}")
    layout = TileLayout3D(
        tuple(w // d for w, d in zip(b_world.shape, dims)), (1, 1, 1)
    )
    if levels is None:
        levels = 1
        while (
            all(c >> levels >= 2 for c in layout.core)
            and all((c >> (levels - 1)) % 2 == 0 for c in layout.core)
        ):
            levels += 1
    specs = level_specs3(layout, topo, tuple(mesh.axis_names), levels)
    cells = float(np.prod(b_world.shape))
    return mesh, dims, specs, tuple(mesh.axis_names), cells


@functools.lru_cache(maxsize=16)
def _mg3_program(mesh, specs, axes, cells, tol, max_cycles, nu,
                 coarse_sweeps, omega, smoother, s_step=1):
    """Compiled-per-config 3D V-cycle solver program."""
    def local(b_tile):
        b = b_tile[0, 0, 0]
        f = b - lax.psum(jnp.sum(b), axes) / cells

        def rs_of(u):
            r = f - periodic_laplacian3(u, specs[0][0])
            return lax.psum(jnp.sum(r * r), axes)

        rs0 = lax.psum(jnp.sum(f * f), axes)
        stop2 = jnp.asarray(tol, f.dtype) ** 2 * rs0

        def cond(st):
            _, rs, prev, k = st
            return (k < max_cycles) & (rs > stop2) & (rs < 0.5 * prev)

        def body(st):
            u, rs, _, k = st
            u = v_cycle3(u, f, specs, 0, nu, coarse_sweeps, omega, smoother,
                         s_step)
            return u, rs_of(u), rs, k + 1

        u0 = jnp.zeros_like(f)
        u, rs, _, k = lax.while_loop(
            cond, body,
            (u0, rs0, jnp.asarray(np.inf, f.dtype), jnp.asarray(0, jnp.int32)),
        )
        u = u - lax.psum(jnp.sum(u), axes) / cells
        tiny = jnp.asarray(np.finfo(np.dtype(f.dtype)).tiny, f.dtype)
        return u[None, None, None], k, jnp.sqrt(rs / jnp.maximum(rs0, tiny))

    return run_spmd(
        mesh,
        local,
        P(*mesh.axis_names, None, None, None),
        (P(*mesh.axis_names, None, None, None), P(), P()),
    )


def mg_poisson3d_solve(
    b_world: np.ndarray,
    mesh: Optional[Mesh] = None,
    *,
    levels: Optional[int] = None,
    tol: float = 1e-5,
    max_cycles: int = 50,
    nu: int = 2,
    coarse_sweeps: int = 32,
    omega: float = 6 / 7,
    smoother: str = "rbgs",
    s_step: int = 1,
):
    """Solve ``A x = b - mean(b)`` (periodic 7-point Laplacian) by 3D
    V-cycles over a 3-axis mesh. Returns ``(x_world, cycles, relres)``
    with zero-mean ``x`` (same contract as the 2D solver, including the
    check-``relres`` convergence caveat on ``mg_poisson_solve``).
    ``s_step > 1`` runs the smoothers communication-avoiding (``s_step``
    sweeps per deep halo exchange) — smoother-level bit-identical by
    the trapezoid validity law, same cycle count, solution equal to
    roundoff."""
    from tpuscratch.solvers.multigrid import warn_unconverged

    mesh, dims, specs, axes, cells = _mg_prologue3(b_world, mesh, levels)
    program = _mg3_program(
        mesh, tuple(specs), axes, cells, float(tol), int(max_cycles),
        int(nu), int(coarse_sweeps), float(omega), smoother, int(s_step),
    )
    x_tiles, k, relres = program(
        jnp.asarray(decompose3d_cores(b_world, dims))
    )
    warn_unconverged("mg_poisson3d_solve", float(relres), tol)
    return assemble3d_cores(np.asarray(x_tiles)), int(k), float(relres)


def pcg_poisson3d_solve(
    b_world: np.ndarray,
    mesh: Optional[Mesh] = None,
    *,
    levels: Optional[int] = None,
    tol: float = 1e-5,
    max_iters: int = 50,
    nu: int = 2,
    coarse_sweeps: int = 16,
    omega: float = 6 / 7,
    smoother: str = "rbgs",
    s_step: int = 1,
):
    """Multigrid-preconditioned CG on the 3D periodic Poisson problem —
    the 2D ``pcg_poisson_solve`` one dimension up, same contract:
    ``(x_world, iters, relres)``, nullspace-projected symmetric V-cycle
    preconditioner, true-residual stopping.  ``s_step`` folds smoothing
    sweeps per halo exchange inside the preconditioner, exactly as in
    ``mg_poisson3d_solve``."""
    from tpuscratch.solvers.multigrid import warn_unconverged

    mesh, dims, specs, axes, cells = _mg_prologue3(b_world, mesh, levels)
    program = _pcg3_program(
        mesh, tuple(specs), axes, cells, float(tol), int(max_iters),
        int(nu), int(coarse_sweeps), float(omega), smoother, int(s_step),
    )
    x_tiles, k, relres = program(
        jnp.asarray(decompose3d_cores(b_world, dims))
    )
    warn_unconverged("pcg_poisson3d_solve", float(relres), tol)
    return assemble3d_cores(np.asarray(x_tiles)), int(k), float(relres)


@functools.lru_cache(maxsize=16)
def _pcg3_program(mesh, specs, axes, cells, tol, max_iters, nu,
                  coarse_sweeps, omega, smoother, s_step=1):
    """Compiled-per-config 3D MG-preconditioned CG program."""
    from tpuscratch.solvers.cg import cg

    def local(b_tile):
        b = b_tile[0, 0, 0]
        f = b - lax.psum(jnp.sum(b), axes) / cells

        def project(v):
            return v - lax.psum(jnp.sum(v), axes) / cells

        def precond(r):
            z = v_cycle3(
                jnp.zeros_like(r), project(r), specs, 0, nu,
                coarse_sweeps, omega, smoother, s_step,
            )
            return project(z)

        x, k, relres = cg(
            lambda p: periodic_laplacian3(p, specs[0][0]),
            f, axes, tol=tol, max_iters=max_iters, precond=precond,
        )
        x = project(x)
        return x[None, None, None], k, relres

    return run_spmd(
        mesh,
        local,
        P(*mesh.axis_names, None, None, None),
        (P(*mesh.axis_names, None, None, None), P(), P()),
    )
