"""The supervised solver runner: long solves on the production machinery.

ROADMAP item 5's gap in one sentence: the trainer and the halo driver
survive preemptions, inject chaos, and account their wall time, while a
multigrid solve is still a single fire-and-forget compiled call — a
walltime kill loses everything, exactly the reference's situation
(per-rank result dumps only, mpi-2d-stencil-subarray.cpp:62).  This
module is the trainer/halo-driver chunk loop pointed at iterative
solvers: the 3D multigrid Poisson solve runs as a sequence of compiled
CHUNKS of V-cycles, the full solver state (solution tiles + the
convergence scalars the stopping rule carries) is checkpointed at every
chunk boundary through the crash-safe publish protocol, and a re-invoked
run resumes BIT-IDENTICAL to an uninterrupted one — chunk boundaries are
deterministic and the ``.npy`` round trip is exact, the same contract
``tests/test_checkpoint_resume.py`` proves for the stencil driver.

The production hooks mirror the other two chunk loops verbatim:

- ``obs``: one ``solver/chunk`` event per chunk (cycles reached, fenced
  wall seconds, cell-updates/s, compile share) + ``ckpt/save`` walls —
  ``obs.goodput.goodput_report`` books them into the step/checkpoint
  buckets, so a solver service's goodput fraction is the same auditable
  number a training run's is;
- ``ft``: ``comm/solver_chunk`` chaos site before each compiled chunk
  (a transient ``CommError`` — the supervisor's restartable class),
  checkpoint saves under ``ft.retry``, and ``solver/preempt`` AFTER the
  save, so the restarted run resumes exactly where the preempted one
  stopped; :func:`supervised_mg3d_solve` wraps the whole loop in
  ``ft.supervisor.supervise``.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.halo.halo3d import assemble3d_cores, decompose3d_cores
from tpuscratch.solvers.multigrid3d import (
    _mg_prologue3,
    periodic_laplacian3,
    v_cycle3,
)

__all__ = ["SolveReport", "checkpointed_mg3d_solve", "mg3d_solve_program",
           "supervised_mg3d_solve"]


@dataclasses.dataclass(frozen=True)
class SolveReport:
    """What one (possibly resumed) supervised solve did — the solver
    sibling of ``TrainReport``/``GenerateReport``."""

    cycles: int          # V-cycles applied in total (across resumes)
    relres: float        # achieved relative residual
    converged: bool      # relres <= tol (False: max_cycles or stagnation)
    chunks: int          # compiled chunk invocations THIS run
    resumed_at: int      # cycle the run picked up from (0 = fresh)


@functools.lru_cache(maxsize=16)
def _mg3_chunk_program(mesh, specs, axes, cells, tol, chunk, max_cycles,
                       nu, coarse_sweeps, omega, smoother, s_step):
    """Compiled chunk: advance the solver state by up to ``chunk``
    V-cycles (stopping early on convergence or stagnation, exactly the
    whole-solve program's rule, so a chunked run walks the same cycle
    sequence).  State is ``(u_tiles, rs, prev, k)`` plus the replicated
    ``rs0`` output the host needs for the stop rule."""
    def local(u_tile, b_tile, rs, prev, k):
        b = b_tile[0, 0, 0]
        u = u_tile[0, 0, 0]
        f = b - lax.psum(jnp.sum(b), axes) / cells
        rs0 = lax.psum(jnp.sum(f * f), axes)
        stop2 = jnp.asarray(tol, f.dtype) ** 2 * rs0
        # a fresh run passes rs=inf sentinels; cycle 0 seeds the true
        # initial residual (recomputed deterministically on resume)
        rs = jnp.where(k == 0, rs0, rs)

        def rs_of(u):
            r = f - periodic_laplacian3(u, specs[0][0])
            return lax.psum(jnp.sum(r * r), axes)

        k_end = jnp.minimum(k + chunk, max_cycles)

        def cond(st):
            _, rs_c, prev_c, k_c = st
            return (k_c < k_end) & (rs_c > stop2) & (rs_c < 0.5 * prev_c)

        def body(st):
            u_c, rs_c, _, k_c = st
            u_c = v_cycle3(u_c, f, specs, 0, nu, coarse_sweeps, omega,
                           smoother, s_step)
            return u_c, rs_of(u_c), rs_c, k_c + 1

        u, rs, prev, k = lax.while_loop(cond, body, (u, rs, prev, k))
        return u[None, None, None], rs, prev, k, rs0

    tile_spec = P(*mesh.axis_names, None, None, None)
    return run_spmd(
        mesh,
        local,
        (tile_spec, tile_spec, P(), P(), P()),
        (tile_spec, P(), P(), P(), P()),
    )


def checkpointed_mg3d_solve(
    b_world: np.ndarray,
    ckpt_dir: str,
    *,
    mesh=None,
    levels: Optional[int] = None,
    tol: float = 1e-5,
    max_cycles: int = 50,
    chunk_cycles: int = 4,
    nu: int = 2,
    coarse_sweeps: int = 32,
    omega: float = 6 / 7,
    smoother: str = "rbgs",
    s_step: int = 1,
    keep: int = 3,
    sink=None,
    chaos=None,
    recorder=None,
    log=lambda s: None,
    reshard: bool = False,
    async_ckpt: bool = False,
) -> tuple[np.ndarray, SolveReport]:
    """``mg_poisson3d_solve`` with preemption survival: V-cycles run in
    compiled chunks of ``chunk_cycles``, the solver state is saved at
    every chunk boundary, and a re-invoked run resumes from the newest
    checkpoint in ``ckpt_dir`` — producing a result BIT-IDENTICAL to an
    uninterrupted run (tests prove it under injected preemption and
    ``CommError`` chaos).  Returns ``(x_world, SolveReport)`` with
    zero-mean ``x``.

    This is a RE-INVOCABLE body in the :func:`ft.supervisor.supervise`
    sense; :func:`supervised_mg3d_solve` is the wrapped form.  ``chaos``
    plugs the fault injector in (``comm/solver_chunk`` before each
    chunk, checkpoint-IO faults through ``save``'s stage hook with the
    save under ``ft.retry``, ``solver/preempt`` after the save);
    ``sink``/``recorder`` receive the same chunk/save telemetry the
    trainer and halo driver emit, in the ``solver/*`` namespace.
    ``s_step`` passes through to the communication-avoiding smoothers.

    ``reshard=True`` makes the resume elastic over the mesh shape: a
    checkpoint whose solution tiles were cut for a different 3D process
    grid is reassembled and re-decomposed onto THIS mesh (the core
    tiles round-trip exactly; the convergence scalars are replicated).
    The continued solve is replay-deterministic on the new mesh — its
    psum groupings differ from the old mesh's, so cross-mesh residual
    trajectories agree to reassociation tolerance, not bitwise.
    ``async_ckpt=True`` switches the chunk-boundary saves to the
    snapshot-then-publish path (``runtime.async_ckpt``), with the
    barrier drained before each snapshot, at preemption points, and at
    exit.
    """
    return mg3d_solve_program(
        b_world, ckpt_dir, mesh=mesh, levels=levels, tol=tol,
        max_cycles=max_cycles, chunk_cycles=chunk_cycles, nu=nu,
        coarse_sweeps=coarse_sweeps, omega=omega, smoother=smoother,
        s_step=s_step, keep=keep, sink=sink, chaos=chaos, recorder=recorder,
        log=log, reshard=reshard, async_ckpt=async_ckpt,
    ).run()


def mg3d_solve_program(
    b_world: np.ndarray,
    ckpt_dir: str,
    *,
    mesh=None,
    levels: Optional[int] = None,
    tol: float = 1e-5,
    max_cycles: int = 50,
    chunk_cycles: int = 4,
    nu: int = 2,
    coarse_sweeps: int = 32,
    omega: float = 6 / 7,
    smoother: str = "rbgs",
    s_step: int = 1,
    keep: int = 3,
    sink=None,
    chaos=None,
    recorder=None,
    log=lambda s: None,
    reshard: bool = False,
    async_ckpt: bool = False,
    workload: str = "solver",
):
    """:func:`checkpointed_mg3d_solve` as a steppable
    ``runtime.chunked.ChunkedProgram`` — same arguments, same
    ``solver/*`` event stream, same bit-identical resume, but each
    ``tick()`` is one compiled chunk of V-cycles, so a ``MeshScheduler``
    can time-slice the solve against other workloads.  ``run()`` returns
    ``(x_world, SolveReport)``; ``workload`` tags every emitted event."""
    from tpuscratch.obs.sink import NullSink
    from tpuscratch.obs.trace import FlightRecorder, emit_phase_totals
    from tpuscratch.runtime import checkpoint
    from tpuscratch.runtime.chunked import (
        ChunkedProgram,
        ChunkResult,
        WorkloadSink,
    )

    if chunk_cycles < 1:
        raise ValueError(f"chunk_cycles must be >= 1, got {chunk_cycles}")
    sink = WorkloadSink(sink if sink is not None else NullSink(), workload)
    rec = recorder if recorder is not None else FlightRecorder()
    mesh, dims, specs, axes, cells = _mg_prologue3(b_world, mesh, levels)
    misses = _mg3_chunk_program.cache_info().misses
    program = _mg3_chunk_program(
        mesh, tuple(specs), axes, cells, float(tol), int(chunk_cycles),
        int(max_cycles), int(nu), int(coarse_sweeps), float(omega),
        smoother, int(s_step),
    )
    # a cache hit is an already-jitted program whose first call will NOT
    # compile (restarts and repeat solves reuse it) — only a fresh
    # program's first chunk carries the compile-dominated bracket
    fresh_program = _mg3_chunk_program.cache_info().misses > misses

    b_tiles = jnp.asarray(decompose3d_cores(b_world, dims))
    f32 = b_tiles.dtype
    state = {
        "u": np.zeros_like(np.asarray(b_tiles)),
        "rs": np.asarray(np.inf, f32),
        "prev": np.asarray(np.inf, f32),
        "k": np.asarray(0, np.int32),
    }
    resumed_at = 0
    if checkpoint.latest_step(ckpt_dir) is not None:
        state, resumed_at, _meta = checkpoint.restore(ckpt_dir, state,
                                                      reshard=reshard)
        if resumed_at > max_cycles:
            raise ValueError(
                f"checkpoint in {ckpt_dir} is at cycle {resumed_at}, beyond "
                f"the requested {max_cycles} — refusing to return an "
                "over-stepped state (use a fresh ckpt_dir)"
            )
        if np.shape(state["u"])[:3] != tuple(dims):
            # elastic resume: the tiles were cut for another process
            # grid — the core decomposition is a pure relayout, so
            # reassemble the world and re-cut it for THIS mesh
            state["u"] = decompose3d_cores(
                assemble3d_cores(np.asarray(state["u"])), dims
            )
        log(f"resuming at cycle {resumed_at}")

    sink.emit(
        "solver/config", solver="mg3d",
        world=f"{b_world.shape[0]}x{b_world.shape[1]}x{b_world.shape[2]}",
        mesh=f"{dims[0]}x{dims[1]}x{dims[2]}", smoother=smoother,
        s_step=int(s_step), chunk=int(chunk_cycles), tol=tol,
        resumed_at=int(resumed_at),
    )

    save_policy = None
    if chaos is not None:
        from tpuscratch.ft.retry import DEFAULT_SAVE_RETRY

        save_policy = DEFAULT_SAVE_RETRY

    sol = {
        "u": jnp.asarray(state["u"]),
        "rs": jnp.asarray(state["rs"]),
        "prev": jnp.asarray(state["prev"]),
        "rs0": None,
        "k_prev": int(state["k"]),
        "chunks": 0,
        "compiled_once": not fresh_program,
    }
    cells_total = float(np.prod(b_world.shape))

    def remake():
        return mg3d_solve_program(
            b_world, ckpt_dir, mesh=mesh, levels=levels, tol=tol,
            max_cycles=max_cycles, chunk_cycles=chunk_cycles, nu=nu,
            coarse_sweeps=coarse_sweeps, omega=omega, smoother=smoother,
            s_step=s_step, keep=keep, sink=sink, chaos=chaos,
            recorder=recorder, log=log, reshard=reshard,
            async_ckpt=async_ckpt, workload=workload,
        )

    def run_chunk(cp, pos):
        fresh = not sol["compiled_once"]
        u, rs, prev, k_arr, rs0 = jax.block_until_ready(
            program(sol["u"], b_tiles, sol["rs"], sol["prev"],
                    jnp.asarray(pos, jnp.int32))
        )
        sol.update(u=u, rs=rs, prev=prev, rs0=rs0, compiled_once=True)
        return int(k_arr), fresh

    def make_event(cp, pos, payload, chunk_sp):
        k_new, fresh = payload
        advanced = k_new - pos
        chunk_s = chunk_sp.seconds
        sol["chunks"] += 1
        sol["k_prev"] = pos
        return ChunkResult(pos=k_new, event={
            "cycle": k_new, "chunk": advanced, "wall_s": round(chunk_s, 6),
            "cell_updates_per_s": round(
                cells_total * max(advanced, 1) / chunk_s, 3),
            "relres2": float(sol["rs"]) / max(float(sol["rs0"]), 1e-30),
            # the first chunk's bracket is compile-dominated wall —
            # the halo driver's convention at chunk granularity
            "compile_s": round(chunk_s, 6) if fresh else 0.0,
        })

    def snapshot(cp, pos):
        snap_state = {"u": np.asarray(sol["u"]),
                      "rs": np.asarray(sol["rs"]),
                      "prev": np.asarray(sol["prev"]),
                      "k": np.asarray(pos, np.int32)}
        return snap_state, {"solver": "mg3d", "tol": tol,
                            "max_cycles": max_cycles}

    def post_boundary(cp, k_new):
        # the stop rules run AFTER the preemption point, exactly where
        # the legacy loop evaluated them
        stop2 = float(tol) ** 2 * float(sol["rs0"])
        if float(sol["rs"]) <= stop2:
            return True
        if k_new < min(sol["k_prev"] + chunk_cycles, max_cycles):
            # the in-program stagnation rule stopped the chunk short
            log(f"stagnated at cycle {k_new} "
                f"(relres^2 "
                f"{float(sol['rs']) / max(float(sol['rs0']), 1e-30):.3e})")
            return True
        return False

    def epilogue(cp):
        emit_phase_totals(cp.sink, cp.rec)
        tiny = float(np.finfo(np.dtype(f32)).tiny)
        rs0 = sol["rs0"]
        if rs0 is None:
            # resumed at/after max_cycles with nothing left to run: the
            # restored rs is the state; rs0 is recomputed host-side
            # (report only — stop decisions always use the device value)
            f_host = b_world.astype(np.float64)
            f_host = f_host - f_host.mean()
            rs0 = float((f_host * f_host).sum())
        relres = float(np.sqrt(float(sol["rs"]) / max(float(rs0), tiny)))
        converged = relres <= tol
        report = SolveReport(
            cycles=int(cp.pos), relres=relres, converged=converged,
            chunks=sol["chunks"], resumed_at=int(resumed_at),
        )
        cp.sink.emit(
            "solver/run", cycles=report.cycles, relres=report.relres,
            converged=report.converged, chunks=report.chunks,
            resumed_at=report.resumed_at,
        )
        cp.sink.flush()
        # mean projection on the HOST (deterministic either path): the
        # assembled world minus its mean — the whole-solve program's
        # final psum projection, reassembled-side
        x = assemble3d_cores(np.asarray(sol["u"]))
        return x - x.mean(dtype=np.float64).astype(x.dtype), report

    return ChunkedProgram(
        workload=workload, prefix="solver", total=max_cycles,
        pos=int(state["k"]), run_chunk=run_chunk, make_event=make_event,
        snapshot=snapshot, epilogue=epilogue, post_boundary=post_boundary,
        span_args=lambda p: {"cycle_begin": p},
        save_span_args=lambda p: {"cycle": p},
        fail_site="comm/solver_chunk", fail_op="solver_chunk",
        preempt_site="solver/preempt", ckpt_dir=ckpt_dir, keep=keep,
        save_retry=save_policy, async_ckpt=async_ckpt, sink=sink,
        recorder=rec, chaos=chaos, log=log, remake=remake,
    )


def supervised_mg3d_solve(
    b_world: np.ndarray,
    ckpt_dir: str,
    *,
    budget=None,
    restartable=None,
    sink=None,
    metrics=None,
    recorder=None,
    log=lambda s: None,
    sleep=time.sleep,
    **solve_kw,
) -> tuple[np.ndarray, SolveReport]:
    """:func:`ft.supervisor.supervise` around
    :func:`checkpointed_mg3d_solve` — the solver's ``supervise_train``.
    Each restart re-invokes the chunked solve, which resumes from
    ``latest_step(ckpt_dir)`` and replays deterministically; a chaos
    plan in ``solve_kw['chaos']`` persists ACROSS restarts, so consumed
    one-shot faults stay consumed in the replay.  Returns the completing
    invocation's ``(x_world, SolveReport)``."""
    from tpuscratch.ft.supervisor import supervise_program

    def factory():
        return mg3d_solve_program(
            b_world, ckpt_dir, sink=sink, recorder=recorder, log=log,
            **solve_kw,
        )

    return supervise_program(factory, budget=budget,
                             restartable=restartable, sink=sink,
                             metrics=metrics, recorder=recorder,
                             log=log, sleep=sleep)
