"""Geometric multigrid V-cycle for the periodic Poisson problem.

The third solver over the framework's operator family: CG (solvers/cg.py)
iterates O(sqrt(cond)) halo-matvecs on the Dirichlet problem, the
spectral method (solvers/spectral.py) diagonalizes the periodic problem
in one FFT round trip; multigrid solves the same periodic system in O(1)
V-cycles of purely local + neighbor work — no global transpose, which is
the regime that wins once the grid outgrows what two all_to_alls can
move cheaply. Measured: grid-size-independent cycle counts (tests
assert it) — 8 cycles to 1e-6 with the default red-black Gauss-Seidel
smoother, 10 with damped Jacobi (~0.25 contraction per V(2,2)-cycle).

Why the PERIODIC problem: cell-centered coarsening (the choice that makes
the inter-level transfers cheap and local) nests exactly on a torus. On a
Dirichlet box the wall sits h/2 from the first cell center, a distance
that doubles every coarsening, and with rediscretized unit-form operators
the boundary mismatch caps V-cycle contraction near ~0.45 and makes the
inter-level scaling empirical (both measured here before the switch). The
torus also exercises the framework's flagship boundary condition — the
periodic 8/4-neighbor halo of the reference's stencil drivers
(/root/reference/stencil2d/mpi-2d-stencil-subarray.cpp:49-52).

TPU-shaped decisions:
- EVERY level reuses the same 2D device mesh with a halved local tile, so
  the only communication anywhere is the halo exchange inside smoothing,
  restriction, and prolongation — all nearest-neighbor ppermutes on ICI.
- VPU-friendly smoothers only: weighted Jacobi (one fused elementwise
  update) or red-black Gauss-Seidel (two fused masked half-updates, the
  default — 8 vs 10 cycles measured); lexicographic GS would serialize
  what XLA vectorizes and is not offered.
- Transfers are the adjoint pair: bilinear (cell-centered) prolongation
  and full-weighting restriction R = P^T/4 ([1,3,3,1]/8 tensor stencil),
  with the continuum (2h)^2/h^2 = 4 scaling on the restricted residual.
  On the torus this is Galerkin-consistent; mean restriction or
  piecewise-constant prolongation each cost ~2x in contraction
  (0.45-0.65, measured).
- One trace: the level recursion unrolls at trace time and the cycle
  loop is a lax.while_loop on the psum'd residual — zero host round
  trips, like CG.

The singular constant mode is handled the spectral solver's way: solve
``A x = b - mean(b)`` and return the zero-mean branch (Jacobi and both
transfers preserve zero-mean on the torus, so the iteration never leaks
into the nullspace beyond rounding).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tpuscratch.comm import run_spmd
from tpuscratch.halo.exchange import HaloSpec, halo_exchange
from tpuscratch.halo.layout import TileLayout
from tpuscratch.runtime.mesh import make_mesh_2d, topology_of

#: 1D full-weighting stencil, the adjoint of cell-centered bilinear
#: interpolation (normalized to sum 1).
_W4 = (0.125, 0.375, 0.375, 0.125)


def _padded(core: jnp.ndarray, spec: HaloSpec) -> jnp.ndarray:
    """Embed a core tile and fill its 1-ghost ring from the torus."""
    p = jnp.zeros(spec.layout.padded_shape, core.dtype)
    p = lax.dynamic_update_slice(p, core, (1, 1))
    return halo_exchange(p, spec)


def periodic_laplacian(core: jnp.ndarray, spec: HaloSpec) -> jnp.ndarray:
    """``A @ core`` for the periodic 5-point operator, shard-local."""
    u = _padded(core, spec)
    return (
        4.0 * u[1:-1, 1:-1]
        - u[:-2, 1:-1] - u[2:, 1:-1] - u[1:-1, :-2] - u[1:-1, 2:]
    )


def jacobi_smooth(u, f, spec: HaloSpec, omega: float, sweeps: int):
    """``sweeps`` damped-Jacobi iterations on ``A u = f`` (diagonal 4)."""
    def body(_, u):
        return u + (omega / 4.0) * (f - periodic_laplacian(u, spec))

    return lax.fori_loop(0, sweeps, body, u)


def _neighbor_sum(u, spec: HaloSpec):
    p = _padded(u, spec)
    return p[:-2, 1:-1] + p[2:, 1:-1] + p[1:-1, :-2] + p[1:-1, 2:]


def rbgs_smooth(u, f, spec: HaloSpec, sweeps: int, reverse: bool = False):
    """``sweeps`` red-black Gauss-Seidel iterations — the VPU-friendly GS:
    each color's update is one fused masked expression over the whole
    tile, so nothing serializes, at the cost of one extra halo exchange
    per sweep vs Jacobi. Measured: V(2,2) cycles to 1e-6 drop 10 -> 8
    and MG-PCG iterations 6-7 -> 5-6 vs omega=0.8 Jacobi (64^2-256^2).

    Checkerboard parity must be GLOBAL: with even core extents every
    tile's origin has even global coords, so the local (i+j) parity IS
    the global one (guarded below). ``reverse`` runs black first — the
    post-smoother order that makes the V-cycle a symmetric operator,
    which PCG requires of its preconditioner.
    """
    h, w = spec.layout.core_h, spec.layout.core_w
    if h % 2 or w % 2:
        raise ValueError(
            f"red-black smoothing needs even core extents, got {h}x{w}"
        )
    ii = jnp.arange(h)[:, None]
    jj = jnp.arange(w)[None, :]
    red = (ii + jj) % 2 == 0
    first, second = (~red, red) if reverse else (red, ~red)

    def half(u, mask):
        return jnp.where(mask, (f + _neighbor_sum(u, spec)) / 4.0, u)

    def body(_, u):
        return half(half(u, first), second)

    return lax.fori_loop(0, sweeps, body, u)


def restrict_fw(r: jnp.ndarray, spec: HaloSpec) -> jnp.ndarray:
    """Full-weighting restriction: [1,3,3,1]/8 tensor stencil over each
    coarse cell's 4x4 fine neighborhood (needs the fine halo)."""
    rp = _padded(r, spec)
    ch, cw = r.shape[0] // 2, r.shape[1] // 2
    acc = jnp.zeros((ch, cw), r.dtype)
    for a, wa in enumerate(_W4):
        for b, wb in enumerate(_W4):
            acc = acc + wa * wb * lax.slice(
                rp, (a, b), (a + 2 * ch - 1, b + 2 * cw - 1), (2, 2)
            )
    return acc


def prolong_bilinear(e: jnp.ndarray, spec: HaloSpec) -> jnp.ndarray:
    """Cell-centered bilinear prolongation: each fine cell is the
    (9, 3, 3, 1)/16 blend of its 4 nearest coarse cells (coarse halo)."""
    ep = _padded(e, spec)
    c = ep[1:-1, 1:-1]
    no, so = ep[:-2, 1:-1], ep[2:, 1:-1]
    we, ea = ep[1:-1, :-2], ep[1:-1, 2:]
    nw, ne = ep[:-2, :-2], ep[:-2, 2:]
    sw, se = ep[2:, :-2], ep[2:, 2:]
    f00 = (9 * c + 3 * no + 3 * we + nw) / 16
    f01 = (9 * c + 3 * no + 3 * ea + ne) / 16
    f10 = (9 * c + 3 * so + 3 * we + sw) / 16
    f11 = (9 * c + 3 * so + 3 * ea + se) / 16
    ch, cw = e.shape
    top = jnp.stack([f00, f01], axis=-1).reshape(ch, 2 * cw)
    bot = jnp.stack([f10, f11], axis=-1).reshape(ch, 2 * cw)
    return jnp.stack([top, bot], axis=1).reshape(2 * ch, 2 * cw)


def level_specs(layout: TileLayout, topo, axes, levels: int) -> list[HaloSpec]:
    """One HaloSpec per level; level l's core is the top core >> l."""
    specs = []
    for l in range(levels):
        th, tw = layout.core_h >> l, layout.core_w >> l
        if th < 1 or tw < 1 or (l < levels - 1 and (th % 2 or tw % 2)):
            raise ValueError(
                f"tile {layout.core_h}x{layout.core_w} does not support "
                f"{levels} levels (level {l} would be {th}x{tw})"
            )
        specs.append(
            HaloSpec(
                layout=TileLayout(th, tw, 1, 1),
                topology=topo,
                axes=axes,
                neighbors=4,
            )
        )
    return specs


def _smooth(u, f, spec: HaloSpec, omega: float, sweeps: int,
            smoother: str, reverse: bool = False):
    """Smoother dispatch; odd-extent levels (possible at the coarsest)
    fall back to Jacobi, where checkerboard parity cannot be global."""
    if smoother == "rbgs" and spec.layout.core_h % 2 == 0 \
            and spec.layout.core_w % 2 == 0:
        return rbgs_smooth(u, f, spec, sweeps, reverse)
    if smoother not in ("jacobi", "rbgs"):
        raise ValueError(f"unknown smoother {smoother!r}")
    return jacobi_smooth(u, f, spec, omega, sweeps)


def v_cycle(
    u, f, specs: list[HaloSpec], level: int = 0,
    nu: int = 2, coarse_sweeps: int = 32, omega: float = 0.8,
    smoother: str = "jacobi",
):
    """One V-cycle on ``A u = f`` at ``level`` (recursion unrolls in trace).

    Post-smoothing runs the smoother in REVERSE color order (rbgs), so
    the whole cycle is a symmetric operator — a requirement when it
    serves as PCG's preconditioner, free otherwise.
    """
    spec = specs[level]
    if level == len(specs) - 1:
        # symmetry needs equal forward/reverse counts: round odd
        # coarse_sweeps up rather than silently de-symmetrizing
        half = (coarse_sweeps + 1) // 2
        u = _smooth(u, f, spec, omega, half, smoother)
        return _smooth(u, f, spec, omega, half, smoother, reverse=True)
    u = _smooth(u, f, spec, omega, nu, smoother)
    r = f - periodic_laplacian(u, spec)
    rc = 4.0 * restrict_fw(r, spec)  # (2h)^2/h^2 keeps the unit-spacing form
    ec = v_cycle(
        jnp.zeros_like(rc), rc, specs, level + 1, nu, coarse_sweeps, omega,
        smoother,
    )
    u = u + prolong_bilinear(ec, specs[level + 1])
    return _smooth(u, f, spec, omega, nu, smoother, reverse=True)


def _mg_prologue(b_world: np.ndarray, mesh: Optional[Mesh], levels: Optional[int]):
    """Shared driver prologue for the multigrid-based solvers: mesh /
    topology / per-level specs, with ``levels`` defaulting to the deepest
    the per-device tile allows (coarsest tile >= 2 in both dims)."""
    from tpuscratch.halo.driver import _setup

    mesh, topo, layout, _ = _setup(
        b_world.shape, mesh, (1, 1), periodic=True, neighbors=4
    )
    if levels is None:
        levels = 1
        while (
            layout.core_h >> levels >= 2
            and layout.core_w >> levels >= 2
            and (layout.core_h >> (levels - 1)) % 2 == 0
            and (layout.core_w >> (levels - 1)) % 2 == 0
        ):
            levels += 1
    specs = level_specs(layout, topo, tuple(mesh.axis_names), levels)
    cells = float(b_world.shape[0] * b_world.shape[1])
    return mesh, topo, layout, specs, tuple(mesh.axis_names), cells


@functools.lru_cache(maxsize=32)
def _mg_program(mesh, specs, axes, cells, tol, max_cycles, nu,
                coarse_sweeps, omega, smoother):
    """Compiled-per-config V-cycle solver program (repeat solves skip
    the ~seconds of re-tracing the driver would otherwise pay)."""
    def local(b_tile):
        b = b_tile[0, 0]
        f = b - lax.psum(jnp.sum(b), axes) / cells  # project out nullspace

        def rs_of(u):
            r = f - periodic_laplacian(u, specs[0])
            return lax.psum(jnp.sum(r * r), axes)

        rs0 = lax.psum(jnp.sum(f * f), axes)
        stop2 = jnp.asarray(tol, f.dtype) ** 2 * rs0

        def cond(st):
            _, rs, prev, k = st
            # stagnation guard: a healthy cycle contracts rs (the SQUARED
            # norm) by ~0.06; under 2x means we are at the f32 residual
            # floor and further cycles only burn time
            return (k < max_cycles) & (rs > stop2) & (rs < 0.5 * prev)

        def body(st):
            u, rs, _, k = st
            u = v_cycle(u, f, specs, 0, nu, coarse_sweeps, omega, smoother)
            return u, rs_of(u), rs, k + 1

        u0 = jnp.zeros_like(f)
        u, rs, _, k = lax.while_loop(
            cond, body,
            (u0, rs0, jnp.asarray(np.inf, f.dtype), jnp.asarray(0, jnp.int32)),
        )
        u = u - lax.psum(jnp.sum(u), axes) / cells  # zero-mean branch
        tiny = jnp.asarray(np.finfo(np.dtype(f.dtype)).tiny, f.dtype)
        return u[None, None], k, jnp.sqrt(rs / jnp.maximum(rs0, tiny))

    return run_spmd(
        mesh,
        local,
        P(*mesh.axis_names, None, None),
        (P(*mesh.axis_names, None, None), P(), P()),
    )


@functools.lru_cache(maxsize=32)
def _pcg_program(mesh, specs, axes, cells, tol, max_iters, nu,
                 coarse_sweeps, omega, smoother):
    """Compiled-per-config MG-preconditioned CG program."""
    from tpuscratch.solvers.cg import cg

    def local(b_tile):
        b = b_tile[0, 0]
        f = b - lax.psum(jnp.sum(b), axes) / cells

        def project(v):
            return v - lax.psum(jnp.sum(v), axes) / cells

        def precond(r):
            # projected V-cycle (P M P): f32 rounding leaks a constant
            # component into r, and on the singular torus operator the
            # V-cycle AMPLIFIES the nullspace without bound — unprojected,
            # PCG stalls at ~1e-4 relres on 256^2 (measured)
            z = v_cycle(
                jnp.zeros_like(r), project(r), specs, 0, nu,
                coarse_sweeps, omega, smoother,
            )
            return project(z)

        x, k, relres = cg(
            lambda p: periodic_laplacian(p, specs[0]),
            f, axes, tol=tol, max_iters=max_iters, precond=precond,
        )
        x = x - lax.psum(jnp.sum(x), axes) / cells
        return x[None, None], k, relres

    return run_spmd(
        mesh,
        local,
        P(*mesh.axis_names, None, None),
        (P(*mesh.axis_names, None, None), P(), P()),
    )


def warn_unconverged(name: str, relres: float, tol: float) -> None:
    """Surface an unconverged return loudly: the stagnation guard exits
    the cycle loop at the f32 residual floor, which can leave
    ``relres > tol`` looking exactly like a normal return. Callers who
    need a guarantee must check ``relres``; this warning is the safety
    net for callers who forget. The 4x slack skips the healthy
    stopped-a-shade-above-the-floor case (~1.6e-6 at tol 1e-6 with rbgs,
    measured — warning there would make every near-floor solve noisy).
    Written as ``not (<=)`` so a NaN residual — divergence, the worst
    case — also warns."""
    if not (relres <= 4 * tol):
        import warnings

        warnings.warn(
            f"{name}: did not reach tol={tol:g} (relres={relres:.3e}) — "
            "stagnated at the dtype residual floor or hit the cycle cap; "
            "check the returned relres",
            RuntimeWarning,
            stacklevel=3,
        )


def mg_poisson_solve(
    b_world: np.ndarray,
    mesh: Optional[Mesh] = None,
    *,
    levels: Optional[int] = None,
    tol: float = 1e-5,
    max_cycles: int = 50,
    nu: int = 2,
    coarse_sweeps: int = 32,
    omega: float = 0.8,
    smoother: str = "rbgs",
):
    """Solve ``A x = b - mean(b)`` (periodic 5-point Laplacian) by
    V-cycles, distributed over a 2D mesh.

    Same contract as ``solvers.spectral.periodic_poisson_fft`` plus the
    iteration report: returns ``(x_world, cycles, relres)`` with
    zero-mean ``x``. ``omega`` applies to the Jacobi smoother/fallback
    only; the default rbgs smoother has no damping knob.

    ``relres`` is the convergence verdict: the stagnation guard may stop
    before ``tol`` when cycles hit the dtype residual floor, so check
    ``relres <= tol`` when the tolerance matters (a ``RuntimeWarning``
    also fires when the return misses tol by more than 4x).
    """
    from tpuscratch.halo.driver import assemble, decompose

    mesh, topo, layout, specs, axes, cells = _mg_prologue(b_world, mesh, levels)
    program = _mg_program(
        mesh, tuple(specs), axes, cells, float(tol), int(max_cycles),
        int(nu), int(coarse_sweeps), float(omega), smoother,
    )
    flat = TileLayout(layout.core_h, layout.core_w, 0, 0)
    u_tiles, k, relres = program(jnp.asarray(decompose(b_world, topo, flat)))
    warn_unconverged("mg_poisson_solve", float(relres), tol)
    return assemble(np.asarray(u_tiles), topo, flat), int(k), float(relres)


def pcg_poisson_solve(
    b_world: np.ndarray,
    mesh: Optional[Mesh] = None,
    *,
    levels: Optional[int] = None,
    tol: float = 1e-5,
    max_iters: int = 50,
    nu: int = 2,
    coarse_sweeps: int = 16,
    omega: float = 0.8,
    smoother: str = "rbgs",
):
    """Multigrid-preconditioned CG on the periodic Poisson problem.

    The two solver families composed: CG's optimal Krylov step sizes with
    one symmetric V-cycle as the preconditioner (nu pre == nu post
    sweeps with the POST-smoother in reverse color order for rbgs — see
    v_cycle — plus the adjoint transfer pair make the V-cycle an SPD
    operator on the zero-mean subspace, which is all PCG needs on the
    semidefinite torus operator; ``omega`` applies to the Jacobi
    smoother/fallback only). Converges in fewer iterations than
    either plain CG (no preconditioner) or V-cycle iteration (no Krylov
    acceleration) — tests assert both. Same contract as
    ``mg_poisson_solve``: returns ``(x_world, iters, relres)``.
    """
    from tpuscratch.halo.driver import assemble, decompose

    mesh, topo, layout, specs, axes, cells = _mg_prologue(b_world, mesh, levels)
    program = _pcg_program(
        mesh, tuple(specs), axes, cells, float(tol), int(max_iters),
        int(nu), int(coarse_sweeps), float(omega), smoother,
    )
    flat = TileLayout(layout.core_h, layout.core_w, 0, 0)
    x_tiles, k, relres = program(jnp.asarray(decompose(b_world, topo, flat)))
    warn_unconverged("pcg_poisson_solve", float(relres), tol)
    return assemble(np.asarray(x_tiles), topo, flat), int(k), float(relres)
